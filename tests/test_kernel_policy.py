"""Fused-kernel policy end to end (ISSUE 13, DESIGN §4c).

The contracts under test:

* ``kernel="reference"`` (the default) is BIT-identical to an
  unspecified kernel — the explicit spelling shares the executable
  cache entry, the fingerprints, and the bits (the committed
  packing/resume/precision goldens pin the default path's values
  untouched; this file pins the spelling equivalence).
* the FUSED path (single-phase precision): one megakernel launch runs
  both inner fixed points with the SAME iteration code — identical
  step counts and statuses, values at float-fusion noise, r* within
  the documented tolerance of the reference root.
* the TILED push-forward contraction equals the reference matvec
  layout numerically (it is the in-kernel step function).
* the bf16 DESCENT RUNG (two-phase precision): converges under the
  ladder contract with its steps counted as descent work, the FOC
  inversion pinned f32, TPU-gated (tests force the gate open), and a
  poisoned rung escalating into the PRECISION_ESCALATED slot with a
  healthy final status.
* at the sweep level quarantine rungs force ``kernel="reference"``
  (the launch-per-loop fallback) and a faulted fused cell recovers
  with every other cell bit-identical.
* fused solves key their own fingerprints: a fused solve can never
  collide with a reference solve in any sidecar/ledger/store.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import aiyagari_hark_tpu.models.household as hh
from aiyagari_hark_tpu.models.equilibrium import (
    household_capital_supply,
    solve_calibration_lean,
)
from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    dense_wealth_operator,
    solve_household,
    stationary_wealth,
    wealth_transition,
)
from aiyagari_hark_tpu.ops.markov import (
    tile_wealth_operator,
    tiled_wealth_push_forward,
)
from aiyagari_hark_tpu.parallel.sweep import _retry_ladder, run_table2_sweep
from aiyagari_hark_tpu.solver_health import CONVERGED
from aiyagari_hark_tpu.utils.config import (
    KERNEL_POLICIES,
    SweepConfig,
    resolve_kernel,
)
from aiyagari_hark_tpu.utils.fingerprint import (
    hashable_kwargs,
    work_fingerprint,
)

# Tiny tier-1 workload (full-size drift/certification is the bench's
# kernel_* phase); 4 cells keep the sweep-level drills fast.
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
          max_bisect=24)
SWEEP = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.0, 0.3))


@pytest.fixture
def model():
    return build_simple_model(labor_states=3, a_count=12, dist_count=60)


@pytest.fixture
def bf16_on_cpu(monkeypatch):
    """Force the TPU-only gate open so the rung itself runs in CI."""
    monkeypatch.setattr(hh, "BF16_RUNG_BACKENDS",
                        hh.BF16_RUNG_BACKENDS + ("cpu",))


# -- policy resolution + fingerprints ---------------------------------------

def test_resolve_kernel_validates():
    assert resolve_kernel("reference").fused is False
    spec = resolve_kernel("fused")
    assert spec.fused and spec.bf16_descent
    assert resolve_kernel(spec) is spec
    with pytest.raises(ValueError, match="kernel policy"):
        resolve_kernel("sorta-fused")
    assert set(KERNEL_POLICIES) == {"reference", "fused"}


def test_hashable_kwargs_drops_explicit_reference_kernel():
    """The no-drift pin: the explicit default spelling must share every
    fingerprint with the bare one, and an unknown policy must raise at
    the canonicalization surface."""
    assert hashable_kwargs({"a_count": 10}) \
        == hashable_kwargs({"a_count": 10, "kernel": "reference"})
    items_fused = hashable_kwargs({"a_count": 10, "kernel": "fused"})
    assert ("kernel", "fused") in items_fused
    with pytest.raises(ValueError, match="kernel policy"):
        hashable_kwargs({"kernel": "mega"})


def test_fused_solves_key_their_own_fingerprints():
    """Cross-policy inequality: a fused solve is structurally
    unaddressable from a reference sidecar/ledger/store group (and the
    CostLedger therefore keys fused executables apart)."""
    ref = work_fingerprint(hashable_kwargs({"a_count": 10}), np.float64)
    fused = work_fingerprint(
        hashable_kwargs({"a_count": 10, "kernel": "fused"}), np.float64)
    assert ref != fused


# -- default-path bit-identity ----------------------------------------------

def test_reference_default_and_explicit_are_bit_identical():
    bare = solve_calibration_lean(3.0, 0.3, **KW)
    expl = solve_calibration_lean(3.0, 0.3, kernel="reference", **KW)
    assert np.asarray(bare.r_star).tobytes() \
        == np.asarray(expl.r_star).tobytes()
    assert np.asarray(bare.capital).tobytes() \
        == np.asarray(expl.capital).tobytes()
    assert int(bare.egm_iters) == int(expl.egm_iters)


# -- the tiled MXU contraction ----------------------------------------------

def test_tiled_push_forward_matches_reference_matvec_layout(model):
    """One tile-shaped contraction == the per-state matvecs + mix, to
    float-fusion noise (the reduction order differs — which is exactly
    why the tiled layout is opt-in, never the bit-pinned default)."""
    pol, _, _, _ = solve_household(1.02, 1.0, model, 0.96, 2.0)
    trans = wealth_transition(pol, 1.02, 1.0, model)
    d = model.dist_grid.shape[0]
    S = dense_wealth_operator(trans, d)
    dist = hh.initial_distribution(model)
    for _ in range(3):
        dist = hh._push_forward_dense(dist, S, model.transition)
    ref = hh._push_forward_dense(dist, S, model.transition)
    tiled = tiled_wealth_push_forward(dist, tile_wealth_operator(S),
                                      model.transition)
    np.testing.assert_allclose(np.asarray(tiled), np.asarray(ref),
                               rtol=1e-12, atol=1e-15)
    assert abs(float(jnp.sum(tiled)) - 1.0) < 1e-12   # mass conserved


# -- the fused supply path --------------------------------------------------

def test_fused_supply_matches_reference_iteration_path(model):
    """Same iteration code ⇒ same step counts and statuses; values at
    float-fusion noise (documented tolerance: 1e-9 relative in f64 —
    the tiled contraction and kernel boundary reorder reductions)."""
    ref = household_capital_supply(0.02, model, 0.96, 2.0, 0.36, 0.08)
    fus = household_capital_supply(0.02, model, 0.96, 2.0, 0.36, 0.08,
                                   kernel="fused")
    assert int(ref.egm_iters) == int(fus.egm_iters)
    assert int(ref.dist_iters) == int(fus.dist_iters)
    assert int(ref.status) == int(fus.status) == CONVERGED
    np.testing.assert_allclose(float(fus.supply), float(ref.supply),
                               rtol=1e-9)
    # both engines certify the same update-norm tol; the fixed points
    # themselves can differ by ~tol/(1-lambda) in the slow mode
    np.testing.assert_allclose(np.asarray(fus.distribution),
                               np.asarray(ref.distribution),
                               rtol=1e-6, atol=1e-8)
    # reference-style phase accounting: all steps are polish steps
    assert int(fus.descent_steps) == 0
    assert int(fus.polish_steps) == int(fus.egm_iters) + int(fus.dist_iters)


def test_fused_lean_equilibrium_r_star_within_budget():
    ref = solve_calibration_lean(3.0, 0.3, **KW)
    fus = solve_calibration_lean(3.0, 0.3, kernel="fused", **KW)
    drift_bp = abs(float(ref.r_star) - float(fus.r_star)) * 1e4
    assert int(fus.status) == CONVERGED
    # the documented budget is 0.1bp at golden tolerances; this smoke
    # config runs r_tol=1e-5, so the honest bound is the bracket width
    assert drift_bp < 2 * KW["r_tol"] * 1e4


def test_fused_vmapped_dispatch_routes_to_lane_grid():
    """The sweep path: a vmapped fused solve must reroute through the
    custom_vmap rule to the lane-grid kernel and agree with the serial
    fused solves lane by lane."""
    crras = jnp.asarray([1.0, 3.0], dtype=jnp.float64)
    batched = jax.jit(jax.vmap(
        lambda c: solve_calibration_lean(c, 0.3, kernel="fused",
                                         **KW).r_star))(crras)
    for i, c in enumerate((1.0, 3.0)):
        serial = solve_calibration_lean(c, 0.3, kernel="fused", **KW)
        np.testing.assert_allclose(float(batched[i]),
                                   float(serial.r_star), rtol=1e-12)


def test_fused_stationary_wealth_dispatches_interpret_kernel(model):
    """``stationary_wealth(kernel='fused')`` prefers the VMEM kernel
    engine off-TPU via interpret mode — same fixed point, same stats."""
    pol, _, _, _ = solve_household(1.02, 1.0, model, 0.96, 2.0)
    ref = stationary_wealth(pol, 1.02, 1.0, model, method="scatter")
    fus = stationary_wealth(pol, 1.02, 1.0, model, kernel="fused")
    np.testing.assert_allclose(np.asarray(fus[0]), np.asarray(ref[0]),
                               rtol=1e-6, atol=1e-8)
    assert int(fus[3]) == int(ref[3])


# -- the bf16 descent rung --------------------------------------------------

def test_bf16_rung_converges_and_counts_descent_steps(model, bf16_on_cpu):
    pol_ref, it_ref, _, st_ref, ph_ref = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed",
        return_phases=True)
    pol, it, _, st, ph = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed", kernel="fused",
        return_phases=True)
    assert int(st) == CONVERGED
    assert not bool(ph.escalated)
    # the rung's steps are descent work: strictly more descent steps
    # than the f32-only ladder, with the polish certifying the same tol
    assert int(ph.descent_steps) > int(ph_ref.descent_steps)
    np.testing.assert_allclose(np.asarray(pol.c_knots),
                               np.asarray(pol_ref.c_knots),
                               rtol=0, atol=1e-4)


def test_bf16_rung_is_tpu_gated_off_by_default(model):
    """Without the forced gate the CPU ladder must be byte-identical to
    the kernel-less mixed solve — the rung is TPU-only."""
    pol_ref, it_ref, _, _, ph_ref = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed",
        return_phases=True)
    pol, it, _, _, ph = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed", kernel="fused",
        return_phases=True)
    assert int(it) == int(it_ref)
    assert int(ph.descent_steps) == int(ph_ref.descent_steps)
    assert np.asarray(pol.c_knots).tobytes() \
        == np.asarray(pol_ref.c_knots).tobytes()


def test_bf16_rung_escalates_on_injected_descent_fault(model, bf16_on_cpu):
    """A poisoned rung escalates (the reused PRECISION_ESCALATED slot)
    and the polish still certifies the caller's tolerance."""
    pol_ref, _, _, _, _ = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed",
        return_phases=True)
    pol, _, _, st, ph = solve_household(
        1.02, 1.0, model, 0.96, 2.0, precision="mixed", kernel="fused",
        return_phases=True, descent_fault_iter=1)
    assert int(st) == CONVERGED
    assert bool(ph.escalated)
    np.testing.assert_allclose(np.asarray(pol.c_knots),
                               np.asarray(pol_ref.c_knots),
                               rtol=0, atol=1e-4)


def test_bf16_rung_distribution_twin(model, bf16_on_cpu):
    pol, _, _, _ = solve_household(1.02, 1.0, model, 0.96, 2.0)
    ref = stationary_wealth(pol, 1.02, 1.0, model, precision="mixed",
                            return_phases=True)
    fus = stationary_wealth(pol, 1.02, 1.0, model, precision="mixed",
                            kernel="fused", return_phases=True)
    assert int(fus[3]) == CONVERGED
    assert not bool(fus[4].escalated)
    np.testing.assert_allclose(np.asarray(fus[0]), np.asarray(ref[0]),
                               rtol=0, atol=1e-8)


def test_bf16_rung_foc_inversion_stays_f32(model, bf16_on_cpu,
                                           monkeypatch):
    """The x^(-1/gamma) inversion must not run on bf16 operands: pin it
    by intercepting inverse_marginal_utility during a rung'd solve."""
    seen = []
    orig = hh.inverse_marginal_utility

    def spy(vp, crra):
        seen.append(jnp.asarray(vp).dtype)
        return orig(vp, crra)

    monkeypatch.setattr(hh, "inverse_marginal_utility", spy)
    solve_household(1.02, 1.0, model, 0.96, 2.0, precision="mixed",
                    kernel="fused")
    assert seen, "spy never fired"
    assert jnp.dtype(jnp.bfloat16) not in {jnp.dtype(d) for d in seen}


# -- sweep-level integration ------------------------------------------------

def test_retry_ladder_forces_reference_kernel():
    rungs = _retry_ladder({"kernel": "fused"})
    assert rungs and all(r.get("kernel") == "reference" for r in rungs)
    # and the huggett/EZ family ladders follow the same rule
    from aiyagari_hark_tpu.scenarios.epstein_zin import (
        _retry_rungs as ez_rungs,
    )
    from aiyagari_hark_tpu.scenarios.huggett import (
        _retry_rungs as hug_rungs,
    )
    assert all(r.get("kernel") == "reference"
               for r in hug_rungs({"kernel": "fused"}))
    assert all(r.get("kernel") == "reference"
               for r in ez_rungs({"kernel": "fused"}))


@pytest.fixture(scope="module")
def fused_sweeps():
    ref = run_table2_sweep(SWEEP, **KW)
    fused = run_table2_sweep(SWEEP.replace(kernel="fused"), **KW)
    return ref, fused


def test_fused_sweep_matches_reference_sweep(fused_sweeps):
    ref, fus = fused_sweeps
    assert (fus.status == CONVERGED).all()
    drift_bp = np.max(np.abs(np.asarray(fus.r_star_pct)
                             - np.asarray(ref.r_star_pct))) * 100.0
    assert drift_bp < 2 * KW["r_tol"] * 1e4


def test_fused_sweep_quarantine_recovers_on_reference_engines(fused_sweeps):
    """An injected persistent fault routes a fused cell through the
    quarantine ladder, whose rungs re-solve at kernel='reference'; the
    other cells stay bit-identical to the clean fused sweep."""
    _, clean = fused_sweeps
    res = run_table2_sweep(SWEEP.replace(kernel="fused"),
                           inject_fault={"cell": 2, "at_iter": 0,
                                         "mode": "nan"}, **KW)
    assert int(res.retries[2]) >= 1
    assert int(res.status[2]) == CONVERGED
    mask = np.ones(len(res.r_star_pct), dtype=bool)
    mask[2] = False
    assert np.asarray(res.r_star_pct)[mask].tobytes() \
        == np.asarray(clean.r_star_pct)[mask].tobytes()
    assert float(res.r_star_pct[2]) == pytest.approx(
        float(clean.r_star_pct[2]), abs=2 * KW["r_tol"] * 100)


def test_sweep_level_bf16_escalation_drill(bf16_on_cpu):
    """The ISSUE 13 escalation drill at sweep level: every cell's rung
    poisoned under kernel='fused' + precision='mixed' — escalations are
    counted in the PRECISION_ESCALATED slot and every cell still
    converges (quarantine sees nothing).  Mode "stall", the established
    sweep-level descent drill: a NaN would poison the descent-only
    bracket trips' excess too and route through quarantine instead."""
    res = run_table2_sweep(SWEEP.replace(kernel="fused"),
                           precision="mixed", descent_fault_iter=1,
                           descent_fault_mode="stall", **KW)
    assert (res.status == CONVERGED).all()
    assert (res.retries == 0).all()
    assert int(res.precision_escalations.sum()) > 0


def test_huggett_and_ez_cells_accept_the_kernel_policy():
    from aiyagari_hark_tpu.scenarios.epstein_zin import solve_ez_cell
    from aiyagari_hark_tpu.scenarios.huggett import solve_huggett_cell

    tiny = dict(labor_states=3, a_count=10, dist_count=32)
    hug = solve_huggett_cell(2.0, 0.3, kernel="fused", r_tol=1e-4,
                             **tiny)
    assert int(hug.status) == CONVERGED
    ez = solve_ez_cell(4.0, 0.3, kernel="fused", r_tol=1e-4,
                       max_bisect=30, **tiny)
    assert np.isfinite(float(ez.r_star))
