"""den Haan (2010) dynamic-forecast accuracy diagnostics
(models/diagnostics.py) — the aggregate-law quality measure the reference
lacks (its only signal is one-step R², which den Haan showed can sit at
0.9999 while the iterated law drifts)."""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.diagnostics import den_haan_forecast
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from fixture_configs import (
    SOLVE_KWARGS,
    diag_parity_configs,
    diag_pinned_configs,
    diag_true_ks_configs,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


@pytest.fixture(scope="module")
def parity_solution():
    # Config + committed warm start: tests/fixture_configs.py.
    agent, econ = diag_parity_configs()
    return solve_ks_economy(agent, econ, **SOLVE_KWARGS["diag_parity"])


def test_forecast_alignment_is_exact_for_pinned_rule(tmp_path):
    """For the slope-pinned deterministic solution the perceived law IS a
    constant, so the dynamic forecast equals exp(intercept) everywhere and
    its error against the settled path is bounded by the outer tolerance."""
    from fixture_configs import solve_with_committed_checkpoint

    # tolerance 1e-3 (was 1e-4): with the residual convergence criterion
    # the pinned solve must now drive |g| under tolerance too, and each
    # factor of 10 costs several relaxation windows on one core; 1e-3
    # keeps the forecast-error bound below the 0.3% assertion.
    # Near-converged committed checkpoint: settling is the cost
    # (fixture_configs.solve_with_committed_checkpoint for semantics).
    agent, econ = diag_pinned_configs()
    sol = solve_with_committed_checkpoint(
        "diag_pinned", tmp_path,
        lambda ck: solve_ks_economy(agent, econ,
                                    **SOLVE_KWARGS["diag_pinned"],
                                    checkpoint_path=ck))
    assert sol.converged and len(sol.records) > 0
    st = den_haan_forecast(sol, t_start=600)
    np.testing.assert_allclose(np.asarray(st.forecast),
                               float(jnp.exp(sol.afunc.intercept[0])),
                               rtol=1e-12)
    # the secant converges on STEP SIZE 1e-4; the residual g (and slow
    # late-path drift) can sit a few x higher — still a fraction of a
    # percent, an order better than the MC-fit rule's forecast
    assert float(st.max_error_pct) < 0.3


def test_panel_rule_forecast_error_moderate(parity_solution):
    """The MC-fit rule (the reference's construction) is bounded as
    MODERATE, not accurate: its EIV-attenuated slope (~1.11) compounds
    sampling deviations off path, so percent-level dynamic error is the
    expected behavior (committed parity run: max 2.28% / mean 0.42%,
    ``results.json``; the full explanation lives in the
    ``models/diagnostics`` module docstring and DESIGN §3).  The engine
    that claims the den Haan "fraction of a percent" standard is the
    pinned one — ``test_forecast_alignment_is_exact_for_pinned_rule``
    asserts its <0.3% bound.  This test catches regressions (a broken
    rule or simulator blows past these bounds) and den Haan's point that
    the diagnostic is strictly worse than the one-step R² suggests."""
    st = den_haan_forecast(parity_solution)
    assert 0.0 < float(st.mean_error_pct) < 5.0
    assert float(st.max_error_pct) < 10.0
    assert np.isfinite(np.asarray(st.forecast)).all()


def test_true_ks_forecast_tracks_aggregate_shocks():
    """In a genuinely stochastic economy the dynamic forecast must follow
    the realized regime switches (correlate with the actual path), not
    just sit at a constant."""
    agent, econ = diag_true_ks_configs()
    sol = solve_ks_economy(agent, econ, **SOLVE_KWARGS["diag_true_ks"])
    st = den_haan_forecast(sol, t_start=200)
    actual = np.asarray(sol.history.A_prev)[201:]
    corr = np.corrcoef(np.asarray(st.forecast), actual)[0, 1]
    assert corr > 0.8
    assert float(st.max_error_pct) < 10.0
