"""Discount-factor heterogeneity (models/heterogeneity.py).

Oracles: exact reduction to the homogeneous engine when all types share
one beta, the stationarity bound beta_max * (1 + r*) < 1, monotonicity
of wealth in patience, and the headline economics — a beta spread
concentrates wealth (higher Gini, fatter top shares) relative to the
homogeneous economy, which is the whole reason beta-dist models exist
(Krusell-Smith 1998 §3; Carroll et al. 2017)."""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.heterogeneity import (
    population_distribution,
    solve_heterogeneous_equilibrium,
    uniform_beta_types,
)
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.utils.stats import get_lorenz_shares, gini

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


ALPHA, DELTA, CRRA, BETA = 0.36, 0.08, 2.0, 0.96


@pytest.fixture(scope="module")
def model():
    return build_simple_model(labor_states=3, a_count=30, dist_count=150)


def test_uniform_beta_types_brackets_center():
    betas = np.asarray(uniform_beta_types(0.96, 0.01, 5))
    assert betas.shape == (5,)
    np.testing.assert_allclose(betas.mean(), 0.96, atol=1e-12)
    assert betas.min() > 0.95 and betas.max() < 0.97
    assert (np.diff(betas) > 0).all()


def test_degenerate_types_reproduce_homogeneous(model):
    """All types at one beta must give the homogeneous equilibrium: same
    bisection, same supply map, so r* agrees to bracket tolerance."""
    hom = solve_bisection_equilibrium(model, BETA, CRRA, ALPHA, DELTA)
    het = solve_heterogeneous_equilibrium(
        model, jnp.full((3,), BETA), jnp.ones(3), CRRA, ALPHA, DELTA)
    np.testing.assert_allclose(float(het.r_star), float(hom.r_star),
                               atol=1e-8)
    np.testing.assert_allclose(float(het.capital), float(hom.capital),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(population_distribution(het)),
                               np.asarray(hom.distribution), atol=1e-8)


@pytest.fixture(scope="module")
def beta_dist_eq(model):
    betas = uniform_beta_types(BETA, 0.012, 4)
    return solve_heterogeneous_equilibrium(
        model, betas, jnp.ones(4), CRRA, ALPHA, DELTA)


def test_equilibrium_clears_and_is_stationary(model, beta_dist_eq):
    het = beta_dist_eq
    assert abs(float(het.excess)) < 1e-4 * float(het.capital)
    # the most patient type must still discount the equilibrium return
    beta_max = float(uniform_beta_types(BETA, 0.012, 4)[-1])
    assert beta_max * (1.0 + float(het.r_star)) < 1.0
    # weights echoed back normalized
    np.testing.assert_allclose(np.asarray(het.weights), 0.25, atol=1e-12)


def test_patient_types_hold_more_wealth(beta_dist_eq):
    tk = np.asarray(beta_dist_eq.type_capital)
    assert (np.diff(tk) > 0).all()
    # patience differences amplify into large wealth differences
    assert tk[-1] > 2.0 * tk[0]


def test_heterogeneous_solver_is_jittable(model):
    """The solver must jit with TRACED betas (a beta-dist calibration
    sweep is a vmap over beta arrays) — regression for the float() on
    the stationarity bound."""
    import jax

    f = jax.jit(lambda b: solve_heterogeneous_equilibrium(
        model, b, jnp.ones(2), CRRA, ALPHA, DELTA, max_bisect=25))
    res = f(jnp.asarray([0.950, 0.965]))
    assert np.isfinite(float(res.r_star))
    assert np.asarray(res.type_capital).shape == (2,)


def test_beta_spread_concentrates_wealth(model, beta_dist_eq):
    """The reason this model family exists: a modest beta spread raises
    wealth concentration substantially over the homogeneous economy."""
    hom = solve_bisection_equilibrium(model, BETA, CRRA, ALPHA, DELTA)
    grid = np.asarray(model.dist_grid)

    def gini_of(dist):
        return gini(grid, np.asarray(dist).sum(axis=1))

    g_hom = gini_of(hom.distribution)
    g_het = gini_of(population_distribution(beta_dist_eq))
    assert g_het > g_hom + 0.05
    # top-20% wealth share rises (Lorenz ordinate at 80% falls)
    lorenz_hom = get_lorenz_shares(
        grid, np.asarray(hom.distribution).sum(axis=1), [0.8])[0]
    lorenz_het = get_lorenz_shares(
        grid, np.asarray(population_distribution(beta_dist_eq)).sum(axis=1),
        [0.8])[0]
    assert lorenz_het < lorenz_hom
