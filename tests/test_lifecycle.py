"""Tests for the finite-horizon life-cycle solver (models/lifecycle.py) —
the working analog of HARK's ``cycles >= 1`` mode that the reference
inherits but never exercises (``cycles=0`` at ``Aiyagari-HARK.py:237``).

Oracles: the terminal consume-everything condition, convergence of the
long-horizon age-0 policy to the infinite-horizon fixed point (the
``cycles=0`` limit), and the textbook hump-shaped wealth profile under a
retirement income path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    consumption_at,
    solve_household,
)
from aiyagari_hark_tpu.models.lifecycle import (
    simulate_cohort,
    solve_lifecycle,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


@pytest.fixture(scope="module")
def model():
    return build_simple_model(labor_states=5, a_count=40)


R, W, BETA, CRRA = 1.02, 1.0, 0.96, 2.0


def test_terminal_age_consumes_everything(model):
    pol = solve_lifecycle(R, W, model, BETA, CRRA, horizon=10)
    assert pol.m_knots.shape[0] == 10
    np.testing.assert_allclose(np.asarray(pol.c_knots[-1]),
                               np.asarray(pol.m_knots[-1]), rtol=1e-12)


def test_consumption_rises_with_age_at_fixed_resources(model):
    """Shorter remaining horizon => higher marginal propensity to consume:
    at the same m, an older agent consumes more."""
    pol = solve_lifecycle(R, W, model, BETA, CRRA, horizon=40)
    m_test = jnp.full((5, 3), 6.0).at[:].set(jnp.asarray([4.0, 6.0, 9.0]))
    ages = [0, 20, 35, 39]
    c_by_age = [np.asarray(jax.vmap(
        lambda mk, ck, mq: jnp.interp(mq, mk, ck))(
            pol.m_knots[t], pol.c_knots[t], m_test)) for t in ages]
    for younger, older in zip(c_by_age[:-1], c_by_age[1:]):
        assert (older >= younger - 1e-9).all()


def test_long_horizon_converges_to_infinite_horizon(model):
    """With many ages ahead, the age-0 policy is the cycles=0 fixed point —
    backward induction and the while_loop solver must agree."""
    inf_policy, _, _, _ = solve_household(R, W, model, BETA, CRRA)
    pol = jax.jit(lambda: solve_lifecycle(R, W, model, BETA, CRRA,
                                          horizon=300))()
    m_test = jnp.tile(jnp.linspace(0.5, 30.0, 12), (5, 1))
    c_inf = np.asarray(consumption_at(inf_policy, m_test))
    c_age0 = np.asarray(jax.vmap(
        lambda mk, ck, mq: jnp.interp(mq, mk, ck))(
            pol.m_knots[0], pol.c_knots[0], m_test))
    np.testing.assert_allclose(c_age0, c_inf, rtol=1e-5)


def test_hump_shaped_wealth_under_retirement(model):
    """Classic life-cycle shape: earn for 45 years, retire on 30% income for
    15 — mean wealth rises through working life, peaks near retirement,
    then is drawn down."""
    horizon, retire_age = 60, 45
    prof = jnp.concatenate([jnp.ones((retire_age,)),
                            jnp.full((horizon - retire_age,), 0.3)])
    pol = solve_lifecycle(R, W, model, BETA, CRRA, horizon=horizon,
                          income_profile=prof)
    out = jax.jit(lambda k: simulate_cohort(pol, R, W, model, 4000, k,
                                            income_profile=prof))(
        jax.random.PRNGKey(0))
    a = np.asarray(out.assets)
    peak = int(a.argmax())
    assert retire_age - 8 <= peak <= retire_age + 2
    assert a[peak] > 4 * a[10]          # accumulation through working life
    assert a[-1] < 0.35 * a[peak]       # retirement drawdown
    assert np.isfinite(np.asarray(out.consumption)).all()


def test_survival_probabilities_lower_saving(model):
    """Mortality risk discounts the future: with survival < 1 everywhere,
    consumption at the same age and resources is higher."""
    pol_immortal = solve_lifecycle(R, W, model, BETA, CRRA, horizon=30)
    pol_mortal = solve_lifecycle(R, W, model, BETA, CRRA, horizon=30,
                                 survival=jnp.full((30,), 0.95))
    m_test = jnp.tile(jnp.linspace(2.0, 20.0, 8), (5, 1))
    c_i = np.asarray(jax.vmap(lambda mk, ck, mq: jnp.interp(mq, mk, ck))(
        pol_immortal.m_knots[0], pol_immortal.c_knots[0], m_test))
    c_m = np.asarray(jax.vmap(lambda mk, ck, mq: jnp.interp(mq, mk, ck))(
        pol_mortal.m_knots[0], pol_mortal.c_knots[0], m_test))
    assert (c_m > c_i).all()


def test_terminal_no_debt_under_borrowing_limit():
    """With a negative borrowing limit the terminal age must still consume
    exactly m (die debt-free), not m - b — and every age's policy must
    keep end-of-life assets feasible."""
    m_debt = build_simple_model(labor_states=3, a_count=24,
                                borrow_limit=-2.0)
    pol = solve_lifecycle(R, W, m_debt, BETA, CRRA, horizon=8)
    np.testing.assert_allclose(np.asarray(pol.c_knots[-1]),
                               np.asarray(pol.m_knots[-1]), rtol=1e-12)
    # simulate a cohort: final-age assets are ~0, never negative
    out = simulate_cohort(pol, R, W, m_debt, 500, jax.random.PRNGKey(3))
    assert abs(float(out.assets[-1])) < 1e-8
