"""Scenario registry (ISSUE 9): the registry contract, row-schema
round-trips, scenario-keyed fingerprints, and the full-pipeline
acceptance for the non-Aiyagari families — Huggett and Epstein-Zin run
the balanced sweep with quarantine, SIGTERM-resume bit-identity, and the
serve paths with certification, exactly like Aiyagari does."""

import os
import signal

import numpy as np
import pytest

from aiyagari_hark_tpu.parallel.sweep import run_sweep, run_table2_sweep
from aiyagari_hark_tpu.scenarios import (
    CellSpace,
    DuplicateScenarioError,
    RowSchema,
    Scenario,
    ScenarioError,
    UnknownScenarioError,
    get_scenario,
    register,
    scenario_names,
    unregister,
)
from aiyagari_hark_tpu.serve import (
    EquilibriumService,
    SolutionStore,
    make_query,
    make_solution,
)
from aiyagari_hark_tpu.solver_health import CONVERGED, is_failure
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.fingerprint import (
    hashable_kwargs,
    solution_fingerprint,
    work_fingerprint,
)
from aiyagari_hark_tpu.utils.resilience import (
    Interrupted,
    LedgerState,
    preemption_guard,
)

# The same tiny-cell Aiyagari configuration as tests/test_serve.py, so
# cross-scenario service tests share compiled executables with the rest
# of the suite.
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)

# Small-but-real Huggett configuration (x64; one shared dict so every
# test in this file addresses ONE executable family per shape).
HKW = dict(a_count=12, dist_count=48, labor_states=3, r_tol=1e-5,
           max_bisect=20, egm_tol=1e-5, dist_tol=1e-9,
           borrow_limit=-2.0)
HCFG = SweepConfig(crra_values=(1.5, 3.0), rho_values=(0.3, 0.6),
                   schedule="balanced", n_buckets=2)

# Tiny Epstein-Zin configuration (cold solves per midpoint are the
# expensive part — keep the budget small).
EKW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
           max_bisect=12, egm_tol=1e-5, dist_tol=1e-8, ez_rho=2.0)
ECFG = SweepConfig(crra_values=(2.0, 6.0), rho_values=(0.3, 0.6),
                   schedule="balanced", n_buckets=2)


def assert_rows_identical(a, b, skip_cells=()):
    """Bitwise equality of two ScenarioSweepResults' rows/status/retries
    (optionally ignoring specific cells)."""
    keep = np.ones(len(a.rows), dtype=bool)
    for i in skip_cells:
        keep[i] = False
    assert np.array_equal(a.rows[keep], b.rows[keep], equal_nan=True)
    assert np.array_equal(a.status[keep], b.status[keep])
    assert np.array_equal(a.retries[keep], b.retries[keep])


# ---------------------------------------------------------------------------
# Registry contract.
# ---------------------------------------------------------------------------

def test_builtins_registered():
    names = scenario_names()
    for name in ("aiyagari", "huggett", "epstein_zin"):
        assert name in names
        scn = get_scenario(name)
        assert scn.name == name
        assert scn.schema.width == len(scn.schema.fields)


def test_unknown_scenario_raises_typed():
    with pytest.raises(UnknownScenarioError) as ei:
        get_scenario("hugget")            # the typo must not auto-create
    assert "hugget" in str(ei.value)
    assert "huggett" in str(ei.value)     # the message lists what exists
    assert isinstance(ei.value, KeyError)
    with pytest.raises(UnknownScenarioError):
        make_query(3.0, 0.6, scenario="not-a-family", **KW)


def test_duplicate_registration_raises():
    scn = get_scenario("huggett")
    with pytest.raises(DuplicateScenarioError):
        register(scn)
    # replace=True is the explicit escape hatch and returns the prior
    prior = register(scn, replace=True)
    assert prior is scn
    # a fresh name registers cleanly and can be removed again
    extra = Scenario(name="huggett-test-clone", schema=scn.schema,
                     cells=scn.cells, batched_solver=scn.batched_solver,
                     eager_row=scn.eager_row, retry_rungs=scn.retry_rungs)
    try:
        register(extra)
        assert get_scenario("huggett-test-clone") is extra
    finally:
        unregister("huggett-test-clone")
    with pytest.raises(UnknownScenarioError):
        get_scenario("huggett-test-clone")


def test_row_schema_validation():
    with pytest.raises(ScenarioError):
        RowSchema(fields=("a", "a", "status"))          # repeated field
    with pytest.raises(ScenarioError):
        RowSchema(fields=("r_star", "status"),
                  counters=("x", "y", "z"))             # roles not in layout
    with pytest.raises(ScenarioError):
        CellSpace(names=("a", "b"), scale=(1.0, 1.0),
                  work=lambda c: c[:, 0])               # not CELL_DIM
    schema = get_scenario("huggett").schema
    assert schema.idx("net_demand") == 1
    with pytest.raises(ScenarioError):
        schema.idx("capital")                           # typed, not ValueError


def test_schema_checksums_distinct_per_layout():
    cks = {get_scenario(n).schema.checksum() for n in scenario_names()}
    # aiyagari (10 fields) / huggett (7) / epstein_zin (7, different
    # names) must all disagree — same-width layouts included
    assert len(cks) == len(scenario_names())


# ---------------------------------------------------------------------------
# Scenario identity in every fingerprint (the structural-collision
# property of the acceptance criteria).
# ---------------------------------------------------------------------------

def test_fingerprints_scenario_keyed_property():
    """For a grid of cells and kwargs variants, the work/solution keys of
    different scenarios NEVER collide — scenario identity is a hashed
    token, so a collision would need md5 to collide, not parameters to
    coincide."""
    rng = np.random.default_rng(7)
    kwargs_variants = [KW, {**KW, "r_tol": 2e-4}, {}]
    names = scenario_names()
    for kw in kwargs_variants:
        items = hashable_kwargs(dict(kw))
        groups = [work_fingerprint(items, np.float64, scenario=n)
                  for n in names]
        assert len(set(groups)) == len(names)
        for _ in range(10):
            cell = rng.uniform([1.0, 0.0, 0.1], [6.0, 0.9, 0.4])
            keys = [solution_fingerprint(cell[0], cell[1], cell[2],
                                         items, np.float64, scenario=n)
                    for n in names]
            assert len(set(keys)) == len(names)


def test_query_keys_scenario_keyed():
    qa = make_query(3.0, 0.6, **KW)
    qh = make_query(3.0, 0.6, scenario="huggett", **KW)
    assert qa.key() != qh.key()
    assert qa.group() != qh.group()


# ---------------------------------------------------------------------------
# Schema <-> checksum <-> ledger <-> store round-trip, per scenario.
# ---------------------------------------------------------------------------

def _synthetic_row(schema):
    row = np.arange(1.0, schema.width + 1.0)
    row[schema.idx(schema.root)] = 0.0371
    row[schema.idx(schema.status)] = float(CONVERGED)
    return row


@pytest.mark.parametrize("name", ["aiyagari", "huggett", "epstein_zin"])
def test_schema_ledger_store_roundtrip(tmp_path, name):
    scn = get_scenario(name)
    schema = scn.schema
    row = _synthetic_row(schema)

    # ledger: record at the scenario's width, flush, resume bit-identical
    path = str(tmp_path / f"{name}_ledger.npz")
    led = LedgerState(path, fingerprint=42, n_cells=3,
                      width=schema.width)
    led.record_bucket(np.asarray([0, 2]), np.stack([row, row * 2.0]), 0)
    led.flush()
    back = LedgerState.resume(path, 42, 3, width=schema.width)
    assert back.resumed
    assert np.array_equal(back.packed[[0, 2]],
                          np.stack([row, row * 2.0]))
    assert not back.solved[1]

    # store: entry carries the schema checksum, lifts root/status by
    # name, round-trips through the disk tier, and refuses a stale
    # schema at read time
    store = SolutionStore(capacity=4,
                          disk_path=str(tmp_path / f"{name}_store"))
    sol = make_solution((3.0, 0.6, 0.2), row, group=7, key=11,
                        schema=schema)
    assert int(sol.schema_ck) == schema.checksum()
    assert float(sol.root) == row[schema.idx(schema.root)]
    assert int(sol.status) == CONVERGED
    store.put(sol)
    got = store.get(11, schema_ck=schema.checksum())
    assert got is not None
    assert np.array_equal(np.asarray(got.packed), row)
    # a DIFFERENT schema checksum is a stale layout: evicted, not served
    other = get_scenario("huggett" if name != "huggett"
                         else "aiyagari").schema
    with pytest.warns(UserWarning, match="stale row schema"):
        assert store.get(11, schema_ck=other.checksum()) is None
    assert store.get(11, schema_ck=schema.checksum()) is None  # gone


def test_cross_scenario_store_never_serves(tmp_path):
    """An aiyagari entry can NEVER answer a huggett query at numerically
    identical parameters: the keys differ structurally, so the store has
    no entry at the huggett address at all."""
    store = SolutionStore(capacity=8)
    qa = make_query(3.0, 0.6, **KW)
    qh = make_query(3.0, 0.6, scenario="huggett", **KW)
    row = _synthetic_row(get_scenario("aiyagari").schema)
    store.put(make_solution(qa.cell(), row, qa.group(), qa.key()))
    assert store.get(qa.key()) is not None
    assert store.get(qh.key()) is None
    # and the donor path is scenario-local too: the huggett group holds
    # no donors even though a numerically identical cell is cached
    assert store.nominate(qh.cell(), qh.group(), 0.1, 1e-6) is None


# ---------------------------------------------------------------------------
# run_sweep("aiyagari") IS run_table2_sweep (the thin-wrapper pin).
# ---------------------------------------------------------------------------

def test_aiyagari_wrapper_is_thin():
    cfg = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9))
    table = run_table2_sweep(cfg, **KW)
    rows = run_sweep("aiyagari", sweep=cfg, **KW)
    assert rows.scenario == "aiyagari"
    assert np.array_equal(rows.col("r_star") * 100.0, table.r_star_pct,
                          equal_nan=True)
    assert np.array_equal(rows.col("capital"), table.capital,
                          equal_nan=True)
    assert np.array_equal(rows.icol("egm_iters"), table.egm_iters)
    assert np.array_equal(rows.status, table.status)


# ---------------------------------------------------------------------------
# Huggett: the full pipeline (balanced sweep + quarantine, SIGTERM
# resume, serve paths + certification).
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def huggett_clean():
    """The reference Huggett run: balanced 4-cell sweep, certified."""
    res = run_sweep("huggett", sweep=HCFG.replace(certify=True), **HKW)
    assert not is_failure(res.status).any()
    assert res.cert_level is not None
    assert (res.cert_level <= 1).all()        # CERTIFIED or MARGINAL
    # the economics: r* below the autarky bound, positive borrower mass
    assert (res.col("r_star") < (1.0 - 0.96) / 0.96).all()
    assert (res.col("borrower_share") > 0.0).all()
    return res


def test_huggett_balanced_sweep_with_quarantine(huggett_clean):
    """An injected NaN at one cell's bisection trips quarantine; the
    retry ladder recovers it and every OTHER cell is bit-identical to
    the clean run."""
    res = run_sweep("huggett", sweep=HCFG,
                    inject_fault={"cell": 1, "at_iter": 2, "mode": "nan"},
                    max_retries=2, **HKW)
    assert int(res.retries[1]) >= 1            # the ladder really ran
    assert not is_failure(res.status).any()    # and recovered
    assert_rows_identical(res, huggett_clean, skip_cells=(1,))
    # the recovered root agrees with the clean one at solver noise
    assert abs(float(res.col("r_star")[1])
               - float(huggett_clean.col("r_star")[1])) < 5e-4


def test_huggett_sigterm_resume_bit_identical(tmp_path, huggett_clean):
    """SIGTERM after bucket 0 raises the typed Interrupted with a valid
    ledger; the resumed run reassembles bit-identically to the clean
    run."""
    ledger = str(tmp_path / "huggett_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted) as ei:
            run_sweep("huggett", sweep=HCFG, resume_path=ledger,
                      inject_preempt={"after_bucket": 0,
                                      "mode": "signal"}, **HKW)
    assert ei.value.signum == signal.SIGTERM
    assert os.path.exists(ledger)
    resumed = run_sweep("huggett", sweep=HCFG, resume_path=ledger, **HKW)
    assert not os.path.exists(ledger)
    assert_rows_identical(resumed, huggett_clean)


def test_huggett_serve_paths_and_certification():
    """One service serves Huggett cold / exact-hit / near (verified
    bracket seeds) with certify-before-cache; served bits equal the
    reference batch-of-1 launch with the same seed."""
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4), donor_cutoff=1.0,
                             certify_before_cache=True)
    cells = [(1.5, 0.3), (3.0, 0.6)]
    futs = [svc.submit(make_query(s, r, scenario="huggett", **HKW))
            for s, r in cells]
    svc.flush()
    cold = [f.result(0) for f in futs]
    assert [r.path for r in cold] == ["cold", "cold"]
    assert all(r.scenario == "huggett" for r in cold)
    assert all(r.cert_level is not None and r.cert_level <= 1
               for r in cold)
    # scenario-specific fields ride the result by name
    assert cold[0].value("borrower_share") > 0.0
    assert np.isnan(cold[0].capital)          # no such field: NaN, not junk

    # exact hits resolve at submit, microseconds, cert level preserved
    for (s, r), base in zip(cells, cold):
        fut = svc.submit(make_query(s, r, scenario="huggett", **HKW))
        assert fut.done()
        hit = fut.result(0)
        assert hit.path == "hit"
        assert hit.r_star == base.r_star
        assert hit.values == base.values

    # near path: a shifted rho gets a verified donor bracket
    futs = [svc.submit(make_query(s, r + 0.05, scenario="huggett",
                                  **HKW)) for s, r in cells]
    svc.flush()
    near = [f.result(0) for f in futs]
    assert "near" in [r.path for r in near]
    # the bit-identity contract: served == reference solve, same seed
    for (s, r), res in zip(cells, near):
        q = make_query(s, r + 0.05, scenario="huggett", **HKW)
        ref = svc.reference_solve(q, bracket_init=res.bracket_init)
        assert res.r_star == ref.r_star
        assert res.values == ref.values
    snap = svc.metrics.snapshot()
    assert snap["serve_scenarios"]["huggett"]["cold"] == 2
    assert snap["serve_scenarios"]["huggett"]["hit"] == 2
    svc.close()


def test_cross_scenario_service_no_hit():
    """End to end: a cached aiyagari solution at (3, 0.6, 0.2) is NOT an
    exact hit for the huggett query at identical parameters — the
    huggett query cold-solves its own (different) answer."""
    svc = EquilibriumService(start_worker=False, max_batch=2,
                             ladder=(1, 2))
    ra = svc.query(3.0, 0.6, **KW)
    assert ra.path == "cold"
    hit = svc.submit(make_query(3.0, 0.6, **KW))
    assert hit.done() and hit.result(0).path == "hit"
    # the SAME numeric parameters under the huggett scenario: no hit
    fut = svc.submit(make_query(3.0, 0.6, scenario="huggett", **KW))
    assert not fut.done()
    svc.flush()
    rh = fut.result(0)
    assert rh.path == "cold" and rh.scenario == "huggett"
    assert rh.r_star != ra.r_star             # different economies
    snap = svc.metrics.snapshot()
    assert set(snap["serve_scenarios"]) == {"aiyagari", "huggett"}
    svc.close()


# ---------------------------------------------------------------------------
# Epstein-Zin: the full pipeline for the second non-Aiyagari family.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ez_clean():
    res = run_sweep("epstein_zin", sweep=ECFG.replace(certify=True),
                    **EKW)
    assert not is_failure(res.status).any()
    assert (res.cert_level <= 1).all()
    # risk aversion alone (gamma up, EIS fixed) strengthens
    # precautionary saving: r* falls in gamma at each rho
    r = res.col("r_star")
    assert r[2] < r[0] and r[3] < r[1]
    return res


def test_ez_collapses_to_crra(ez_clean):
    """At gamma == ez_rho the EZ equilibrium IS the CRRA equilibrium
    (up to the lean solver's warm-carry inner noise)."""
    ai = run_sweep("aiyagari",
                   sweep=SweepConfig(crra_values=(2.0,),
                                     rho_values=(0.3,)),
                   **{k: v for k, v in EKW.items() if k != "ez_rho"})
    diff = abs(float(ez_clean.col("r_star")[0])
               - float(ai.col("r_star")[0]))
    assert diff < 5e-4


def test_ez_quarantine_and_resume(tmp_path, ez_clean):
    """Fault injection quarantines and recovers; SIGTERM resume is
    bit-identical — the same machinery, third family."""
    res = run_sweep("epstein_zin", sweep=ECFG,
                    inject_fault={"cell": 2, "at_iter": 1, "mode": "nan"},
                    max_retries=2, **EKW)
    assert int(res.retries[2]) >= 1
    assert not is_failure(res.status).any()
    assert_rows_identical(res, ez_clean, skip_cells=(2,))

    ledger = str(tmp_path / "ez_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_sweep("epstein_zin", sweep=ECFG, resume_path=ledger,
                      inject_preempt={"after_bucket": 0,
                                      "mode": "signal"}, **EKW)
    resumed = run_sweep("epstein_zin", sweep=ECFG, resume_path=ledger,
                        **EKW)
    assert_rows_identical(resumed, ez_clean)


def test_ez_serve_cold_only():
    """The cold-only scenario serves exact hits and cold misses (near is
    structurally absent: Scenario.warm is None) with certification."""
    scn = get_scenario("epstein_zin")
    assert scn.warm is None and scn.warm_mode == "cold-only"
    svc = EquilibriumService(start_worker=False, max_batch=2,
                             ladder=(1, 2), certify_before_cache=True)
    r0 = svc.query(2.0, 0.3, scenario="epstein_zin", **EKW)
    assert r0.path == "cold" and r0.bracket_init is None
    assert r0.cert_level is not None and r0.cert_level <= 1
    fut = svc.submit(make_query(2.0, 0.3, scenario="epstein_zin", **EKW))
    assert fut.done() and fut.result(0).path == "hit"
    # a neighbor query has a donor in range but NO warm machinery: it
    # must be an honest cold, never a fabricated near
    r1 = svc.query(2.0, 0.35, scenario="epstein_zin", **EKW)
    assert r1.path == "cold"
    svc.close()


# ---------------------------------------------------------------------------
# The row-schema lint (ISSUE 9 satellite).
# ---------------------------------------------------------------------------

def _load_lint():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_row_schema",
        os.path.join(repo, "scripts", "check_row_schema.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_row_schema_lint_repo_clean():
    mod, repo = _load_lint()
    findings = mod.scan(repo)
    assert findings == [], "\n".join(
        f"{r}:{ln}: {m}" for r, ln, m in findings)


def test_row_schema_lint_fixtures():
    mod, repo = _load_lint()
    bad = "from aiyagari_hark_tpu.utils.config import PACKED_ROW_FIELDS\n"
    assert mod.scan_source(bad, "aiyagari_hark_tpu/foo.py")
    waived = ("from aiyagari_hark_tpu.utils.config import "
              "PACKED_ROW_FIELDS  # row-schema-ok\n")
    assert not mod.scan_source(waived, "aiyagari_hark_tpu/foo.py")
    attr = "w = config.PACKED_ROW_WIDTH\n"
    assert mod.scan_source(attr, "aiyagari_hark_tpu/foo.py")
    # scenarios/ builds the schema FROM the constant: allowed
    path = os.path.join(repo, "aiyagari_hark_tpu", "scenarios",
                        "aiyagari.py")
    assert mod.scan_file(
        path, os.path.join("aiyagari_hark_tpu", "scenarios",
                           "aiyagari.py")) == []
