"""Execute the multi-process (DCN) path for real (VERDICT r2 next-round
item 7): two local processes, a local coordinator, `jax.distributed`
actually initialized, a global mesh spanning both processes' devices, and
one cross-process collective — not just the single-process no-op branch.

Each child forces the CPU platform via ``jax.config.update`` (NEVER the
``JAX_PLATFORMS`` env var — the axon platform plugin hangs on it, see
``tests/conftest.py``) and exposes 2 virtual devices, so the global mesh
has 4 devices across 2 processes and the final reduction must ride the
distributed runtime.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import os, sys
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
sys.path.insert(0, {repo!r})
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from aiyagari_hark_tpu.parallel import multihost

pid = int(sys.argv[1]); port = sys.argv[2]
ok = multihost.initialize(f"localhost:{{port}}", 2, pid)
assert ok, "initialize() took the single-process no-op branch"
assert multihost.process_count() == 2
devs = jax.devices()
assert len(devs) == 4, f"global device view, got {{len(devs)}}"
assert len(jax.local_devices()) == 2

mesh = Mesh(np.asarray(devs), ("cells",))
# each process contributes its local shard (values pid+1), the jitted
# reduction gathers across processes: 2*(1.0) + 2*(2.0) = 6.0
from jax.experimental import multihost_utils  # noqa: E402
local = np.full((2,), float(pid + 1))
g = multihost_utils.host_local_array_to_global_array(local, mesh, P("cells"))
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(g)
# replicated-but-global output: read this process's local replica
val = float(np.asarray(total.addressable_shards[0].data))
assert val == 6.0, val
if multihost.is_coordinator():
    assert pid == 0
    print("COORD_OK", val)
else:
    print("WORKER_OK", val)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_collective():
    port = _free_port()
    child = _CHILD.format(repo=REPO)
    procs = [
        subprocess.Popen([sys.executable, "-c", child, str(pid), str(port)],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, cwd=REPO)
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-process job hung (coordinator handshake?)")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, f"child failed rc={rc}\nstdout:{out}\nstderr:{err[-2000:]}"
    combined = "\n".join(o for _, o, _ in outs)
    assert combined.count("COORD_OK 6.0") == 1, combined
    assert combined.count("WORKER_OK 6.0") == 1, combined


def test_initialize_refuses_silent_duplicate_jobs(monkeypatch):
    """num_processes>1 with no coordinator must raise, not fork into N
    independent duplicate runs."""
    from aiyagari_hark_tpu.parallel import multihost

    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    monkeypatch.delenv("MEGASCALE_COORDINATOR_ADDRESS", raising=False)
    with pytest.raises(ValueError, match="refusing"):
        multihost.initialize(num_processes=4)


# -- unit tests of the resolution contract (ISSUE 20 satellite): no pod,
# -- no subprocess — jax.distributed.initialize is captured, never run.

@pytest.fixture
def captured_init(monkeypatch):
    """Monkeypatch ``jax.distributed.initialize`` to record its kwargs;
    also scrub every env var ``multihost.initialize`` consults so each
    test states its own environment explicitly."""
    import jax

    calls = []
    monkeypatch.setattr(
        jax.distributed, "initialize",
        lambda **kw: calls.append(kw))
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    return calls


def test_initialize_single_process_noop_touches_nothing(captured_init):
    """No coordinator anywhere -> False, and the distributed runtime is
    never contacted (the recorded call list stays empty)."""
    from aiyagari_hark_tpu.parallel import multihost

    assert multihost.initialize() is False
    assert captured_init == []


def test_initialize_env_var_resolution(captured_init, monkeypatch):
    """The documented order: the JAX_* env vars fill unset arguments
    (ints parsed, not passed as strings)."""
    from aiyagari_hark_tpu.parallel import multihost

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "envhost:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "3")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert multihost.initialize() is True
    assert captured_init == [{"coordinator_address": "envhost:1234",
                              "num_processes": 3, "process_id": 2}]


def test_initialize_explicit_args_beat_env_vars(captured_init,
                                                monkeypatch):
    """Explicit parameters win over the env vars, per argument — an env
    var only fills an argument the caller left unset."""
    from aiyagari_hark_tpu.parallel import multihost

    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "envhost:1234")
    monkeypatch.setenv("JAX_NUM_PROCESSES", "3")
    monkeypatch.setenv("JAX_PROCESS_ID", "2")
    assert multihost.initialize("arghost:9", process_id=0) is True
    assert captured_init == [{"coordinator_address": "arghost:9",
                              "num_processes": 3, "process_id": 0}]


def test_initialize_pod_runtime_autodetection(captured_init, monkeypatch):
    """A pod runtime marker (TPU_WORKER_HOSTNAMES) hands everything to
    the platform's own autodetection: initialize() is called with only
    None arguments and the function reports True."""
    from aiyagari_hark_tpu.parallel import multihost

    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1")
    assert multihost.initialize() is True
    assert captured_init == [{"coordinator_address": None,
                              "num_processes": None, "process_id": None}]


def test_refusal_names_the_duplicate_job_count(captured_init):
    """The refusal is typed AND actionable: the message names the
    requested process count, and the runtime was never touched."""
    from aiyagari_hark_tpu.parallel import multihost

    with pytest.raises(ValueError, match="4 independent duplicate"):
        multihost.initialize(num_processes=4, process_id=0)
    assert captured_init == []


def test_is_coordinator_guard(monkeypatch):
    """is_coordinator() is exactly the process-0 guard."""
    import jax

    from aiyagari_hark_tpu.parallel import multihost

    monkeypatch.setattr(jax, "process_index", lambda: 0)
    assert multihost.is_coordinator() is True
    monkeypatch.setattr(jax, "process_index", lambda: 3)
    assert multihost.is_coordinator() is False
