"""Worker-side chaos seams (ISSUE 16): the ``ChaosAgent`` fault surface
and the shared store's behavior under each injected fault — a partition
read degrades to a miss WITHOUT evicting healthy bytes, a stalled
heartbeat loses its lease to a TTL reclaim and the loss is detected and
counted, a skewed staleness clock forces the duplicated election, and
the heartbeat daemon provably stops on close / last release (no thread
outlives the store).
"""

import os
import threading
import time

import numpy as np
import pytest

from aiyagari_hark_tpu.scenarios.aiyagari import AIYAGARI_SCHEMA
from aiyagari_hark_tpu.serve.chaos import ChaosAgent
from aiyagari_hark_tpu.serve.store import SolutionStore, make_solution
from aiyagari_hark_tpu.utils.checkpoint import (
    acquire_lease,
    break_stale_lease,
    lease_age_s,
)


class _RecObs:
    """Event recorder standing in for an obs scope."""

    def __init__(self):
        self.events = []

    def event(self, etype, **fields):
        self.events.append((etype, dict(fields)))

    def of(self, etype):
        return [f for t, f in self.events if t == etype]


def _row(key):
    rng = np.random.default_rng(key)
    row = rng.standard_normal(len(AIYAGARI_SCHEMA.fields))
    row[AIYAGARI_SCHEMA.idx(AIYAGARI_SCHEMA.status)] = 0.0
    row[AIYAGARI_SCHEMA.idx(AIYAGARI_SCHEMA.root)] = 0.01 + key * 1e-4
    return row


def _store(tmp_path, owner, ttl=30.0, chaos=None, capacity=8):
    s = SolutionStore(disk_path=str(tmp_path / "shared"), shared=True,
                      lease_ttl_s=ttl, owner=owner, capacity=capacity)
    if chaos is not None:
        s.set_chaos(chaos)
    return s


# -- ChaosAgent unit behavior ------------------------------------------------

def test_arm_is_partial_and_explicit_zero_disarms():
    a = ChaosAgent()
    st = a.arm({"slow_publish_s": 2.0, "slow_cells": [(1.0, 0.0, 0.2)]})
    assert st["slow_publish_s"] == 2.0
    st = a.arm({"heartbeat_stall": True})      # untouched keys persist
    assert st["slow_publish_s"] == 2.0 and st["heartbeat_stall"]
    st = a.arm({"slow_publish_s": 0.0, "heartbeat_stall": False})
    assert st["slow_publish_s"] == 0.0 and not st["heartbeat_stall"]


def test_publish_delay_fires_only_for_armed_cells():
    obs = _RecObs()
    a = ChaosAgent(obs=obs, owner="w0")
    a.arm({"slow_publish_s": 1.5, "slow_cells": [(1.0, 0.0, 0.2)]})
    assert a.publish_delay_s((3.0, 0.3, 0.2)) == 0.0   # not armed
    assert obs.of("FLEET_CHAOS_INJECT") == []          # no phantom firing
    assert a.publish_delay_s((1.0, 0.0, 0.2)) == 1.5
    fired = obs.of("FLEET_CHAOS_INJECT")
    assert len(fired) == 1 and fired[0]["drill"] == "slow_publish"
    assert a.armed()["fired"] == 1


def test_heartbeat_stall_fires_once_stays_stalled():
    obs = _RecObs()
    a = ChaosAgent(obs=obs)
    assert a.heartbeat_stalled() is False
    a.arm({"heartbeat_stall": True})
    assert a.heartbeat_stalled() is True
    assert a.heartbeat_stalled() is True       # still stalled...
    assert len(obs.of("FLEET_CHAOS_INJECT")) == 1   # ...journaled ONCE
    a.arm({"heartbeat_stall": False})
    assert a.heartbeat_stalled() is False


def test_partition_reads_count_down():
    obs = _RecObs()
    a = ChaosAgent(obs=obs)
    a.arm({"partition_reads": 2})
    assert [a.read_fault(7), a.read_fault(7), a.read_fault(7)] == [
        True, True, False]
    assert len(obs.of("FLEET_CHAOS_INJECT")) == 2


def test_skew_now_shifts_the_wall_and_fires_once():
    obs = _RecObs()
    a = ChaosAgent(obs=obs)
    assert a.skew_now() is None
    a.arm({"lease_skew_s": 120.0})
    now = a.skew_now()
    assert now is not None and now - time.time() > 100.0
    a.skew_now()
    assert len(obs.of("FLEET_CHAOS_INJECT")) == 1
    a.arm({"lease_skew_s": 0.0})
    assert a.skew_now() is None


# -- the store under each fault ---------------------------------------------

def test_partition_read_degrades_to_miss_without_eviction(tmp_path):
    key = 42
    writer = _store(tmp_path, "w0")
    assert writer.claim(key) == "won"
    writer.publish(make_solution((1.0 + key, 0.5, 0.2), _row(key),
                                 group=777, key=key))
    writer.close()

    agent = ChaosAgent(owner="w1")
    agent.arm({"partition_reads": 1})
    reader = _store(tmp_path, "w1", chaos=agent)
    assert reader.get(key) is None             # the partitioned window
    assert reader.fleet_counts()["fleet_backend_faults"] == 1
    # transient is NOT corrupt: nothing evicted, bytes intact, and the
    # very next read serves the exact published row
    assert reader.integrity_counts()["store_corrupt_evictions"] == 0
    got = reader.get(key)
    assert got is not None
    assert np.array_equal(np.asarray(got.packed), _row(key))
    reader.close()


def test_heartbeat_stall_loses_the_lease_and_is_detected(tmp_path):
    key = 9
    agent = ChaosAgent(owner="w0")
    agent.arm({"heartbeat_stall": True})       # stalled from the start
    zombie = _store(tmp_path, "w0", ttl=0.4, chaos=agent)
    assert zombie.claim(key) == "won"
    time.sleep(0.7)                            # age past the TTL, unbeaten
    peer = _store(tmp_path, "w1", ttl=0.4)
    assert peer.reclaim_if_stale(key) is True
    assert peer.claim(key) == "won"            # the re-election
    agent.arm({"heartbeat_stall": False})      # the zombie wakes...
    deadline = time.time() + 5.0
    while time.time() < deadline:
        if zombie.heartbeat_health()["lost_leases"] >= 1:
            break
        time.sleep(0.05)
    health = zombie.heartbeat_health()
    assert health["lost_leases"] == 1          # ...and DETECTS the theft
    assert zombie.held_leases() == []
    # its late release is owner-checked away: the heir keeps the lease
    zombie.release(key)
    assert peer.lease_present(key)
    peer.release(key)
    zombie.close()
    peer.close()


def test_skewed_clock_forces_duplicated_election(tmp_path):
    key = 5
    holder = _store(tmp_path, "w0", ttl=30.0)
    assert holder.claim(key) == "won"          # fresh, beating, TTL 30
    obs = _RecObs()
    agent = ChaosAgent(obs=obs, owner="w1")
    agent.arm({"lease_skew_s": 200.0})         # reclaimer runs ttl*6 ahead
    skewed = _store(tmp_path, "w1", ttl=30.0, chaos=agent)
    assert skewed.claim(key) == "won"          # stole the FRESH lease
    assert skewed.fleet_counts()["fleet_lease_reclaims"] == 1
    assert [f["drill"] for f in obs.of("FLEET_CHAOS_INJECT")] == [
        "clock_skew"]
    holder.close()
    skewed.close()


# -- heartbeat-thread lifecycle (ISSUE 16 satellite) -------------------------

def _hb_threads():
    return [t for t in threading.enumerate()
            if t.name == "lease-heartbeat" and t.is_alive()]


def test_close_while_held_stops_the_thread_keeps_the_lease(tmp_path):
    s = _store(tmp_path, "w0", ttl=0.5)
    assert s.claim(11) == "won"
    assert s.heartbeat_health()["thread_alive"]
    s.close()
    assert s.heartbeat_health()["thread_alive"] is False
    assert s.heartbeat_health()["closed"] is True
    assert _hb_threads() == []                 # no thread outlives close
    # the held lease is LEFT for TTL reclaim (crashed-winner protocol)
    audit = _store(tmp_path, "audit", ttl=0.5)
    assert audit.lease_present(11)
    s.close()                                  # idempotent
    audit.close()


def test_close_release_leases_true_releases_first(tmp_path):
    s = _store(tmp_path, "w0")
    assert s.claim(12) == "won"
    s.close(release_leases=True)
    assert _hb_threads() == []
    audit = _store(tmp_path, "audit")
    assert not audit.lease_present(12)
    audit.close()


def test_last_release_stops_the_heartbeat_thread(tmp_path):
    s = _store(tmp_path, "w0", ttl=0.4)
    assert s.claim(13) == "won"
    assert s.claim(14) == "won"
    assert s.heartbeat_health()["thread_alive"]
    s.release(13)
    assert s.heartbeat_health()["held"] == 1   # still one held: thread on
    s.release(14)                              # the LAST release
    deadline = time.time() + 5.0
    while time.time() < deadline and s.heartbeat_health()["thread_alive"]:
        time.sleep(0.05)
    assert s.heartbeat_health()["thread_alive"] is False
    assert _hb_threads() == []
    s.close()


# -- clock-skew hardening at the checkpoint layer ---------------------------

def test_lease_age_clamps_a_backwards_clock(tmp_path):
    # regression (ISSUE 16 satellite): mtime AHEAD of the wall (clock
    # stepped back after the acquire) must clamp to age 0, and a
    # backwards ``now`` must never let the staleness breaker fire
    p = str(tmp_path / "x.lease")
    assert acquire_lease(p, owner="a")
    future = time.time() + 500.0
    os.utime(p, (future, future))
    assert lease_age_s(p) == 0.0
    assert break_stale_lease(p, 0.01) is False
    assert break_stale_lease(p, 0.01, now=time.time() - 3600.0) is False
    assert os.path.exists(p)


def test_break_stale_tolerance_window(tmp_path):
    p = str(tmp_path / "y.lease")
    assert acquire_lease(p, owner="a")
    now = time.time()
    assert break_stale_lease(p, 1.0, now=now + 3.0,
                             tolerance_s=5.0) is False   # inside window
    assert break_stale_lease(p, 1.0, now=now + 60.0,
                             tolerance_s=5.0) is True    # beyond it
