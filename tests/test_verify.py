"""Solution-integrity layer (ISSUE 6, DESIGN §9): a posteriori
certification properties, the checksummed artifact chain, and the
SDC spot-recheck — every detection path driven by its deterministic
corruption injector.

The load-bearing acceptance tests:

* every cell of the 12-cell Table II sweep certifies CERTIFIED at
  default thresholds, under the reference AND mixed precision policies,
  with verdicts stable across ``schedule=``;
* a deliberately perturbed policy (one-gridpoint shift, 1e-6 lane
  noise) certifies FAILED;
* every injected corruption — ledger row bit flip, sidecar content
  flip, post-solve lane flip — is detected by the layer that first
  loads or certifies it and degrades (recompute/quarantine/heuristic)
  without poisoning other cells (injected == detected).
"""

import os
import warnings

import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import (
    solve_calibration,
    solve_calibration_lean,
)
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep, sdc_sample
from aiyagari_hark_tpu.solver_health import NONFINITE
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.checkpoint import (
    load_sweep_sidecar,
    save_sweep_sidecar,
)
from aiyagari_hark_tpu.utils.fingerprint import (
    IntegrityError,
    content_checksum,
    packed_row_checksum,
    packed_row_checksums,
    verify_packed_row,
)
from aiyagari_hark_tpu.utils.resilience import Interrupted, clear_interrupt
from aiyagari_hark_tpu.verify import (
    CERT_CHECKS,
    CERTIFIED,
    FAILED,
    CertThresholds,
    certify_equilibrium,
    corrupt_ledger_row,
    flip_row_bit,
    perturbed_policy,
)

# Reduced-size config (test_sweep_scheduler's scale): full production
# code paths, ~1s/cell on CPU.
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
SMALL = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
TWELVE = SweepConfig()


# ---------------------------------------------------------------------------
# Checksum primitives.
# ---------------------------------------------------------------------------

def test_checksum_primitives_deterministic_and_sensitive():
    row = np.asarray([0.035, 5.0, 0.9, 11, 500, 4000, 0, 0, 4500, 0],
                     dtype=np.float64)
    c = packed_row_checksum(row)
    assert c == packed_row_checksum(row.copy())          # deterministic
    assert c != packed_row_checksum(flip_row_bit(row))   # 1-bit sensitive
    assert c != packed_row_checksum(row.astype(np.float32))  # via cast drift
    # shape rides the hash: a flattened 2-row block != its concatenation
    assert (content_checksum(np.zeros((2, 3)))
            != content_checksum(np.zeros(6)))
    # per-row vector agrees with the scalar primitive, NaN rows included
    rows = np.stack([row, np.full(10, np.nan)])
    per = packed_row_checksums(rows)
    assert per[0] == c
    assert per[1] == packed_row_checksum(rows[1])
    verify_packed_row(row, c, "test")                    # clean: no raise
    with pytest.raises(IntegrityError) as ei:
        verify_packed_row(flip_row_bit(row), c, "test", key=7)
    assert ei.value.boundary == "test" and ei.value.key == 7


def test_uncertified_sentinel_pinned():
    """serve.store inlines verify.UNCERTIFIED to stay import-cheap — the
    two spellings must never drift."""
    from aiyagari_hark_tpu.serve.store import UNCERTIFIED as store_u
    from aiyagari_hark_tpu.verify import UNCERTIFIED as verify_u

    assert store_u == verify_u


# ---------------------------------------------------------------------------
# Certification properties.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def solved_cell():
    return solve_calibration(3.0, 0.6, **KW)


def test_full_result_certifies_certified(solved_cell):
    cert = certify_equilibrium(solved_cell, crra=3.0, labor_ar=0.6, **KW)
    assert cert.level == CERTIFIED and cert.certified
    assert tuple(c.name for c in cert.checks) == CERT_CHECKS
    assert all(np.isfinite(c.residual) for c in cert.checks)


def test_lean_and_bare_rstar_certify(solved_cell):
    lean = solve_calibration_lean(3.0, 0.6, **KW)
    cert = certify_equilibrium(lean, crra=3.0, labor_ar=0.6, **KW)
    assert cert.certified
    # a bare float r*: the capital claim is mirrored (nothing to check)
    bare = certify_equilibrium(float(lean.r_star), crra=3.0,
                               labor_ar=0.6, **KW)
    assert bare.certified
    assert bare.residuals()["capital"] == 0.0


@pytest.mark.parametrize("mode,amplitude", [("shift", 0.0),
                                            ("noise", 1e-6)])
def test_perturbed_policy_certifies_failed(solved_cell, mode, amplitude):
    """ISSUE 6 acceptance: a finite, monotone-looking, plausible policy —
    one-gridpoint shift or 1e-6 lane noise — must FAIL certification
    (only the independent oracles can catch it; no status code fires)."""
    bad = solved_cell._replace(
        policy=perturbed_policy(solved_cell.policy, mode=mode,
                                amplitude=amplitude))
    cert = certify_equilibrium(bad, crra=3.0, labor_ar=0.6, **KW)
    assert cert.failed, cert.summary()


def test_perturbed_rstar_certifies_failed(solved_cell):
    """A corrupted interest rate (the serve-path lane-perturbation
    amplitude) fails the full-path market-clearing re-evaluation."""
    cert = certify_equilibrium(float(solved_cell.r_star) + 3e-3,
                               crra=3.0, labor_ar=0.6, **KW)
    assert cert.failed
    assert cert.worst().name in ("market_clearing", "capital")


def test_failed_status_row_certifies_failed_without_recompute():
    from aiyagari_hark_tpu.parallel.sweep import (
        _canonical_dtype,
        _hashable_kwargs,
    )
    from aiyagari_hark_tpu.verify import certify_packed_rows

    row = np.asarray([np.nan, np.nan, 1.0, 5, 100, 100, NONFINITE,
                      0, 200, 0], dtype=np.float64)
    certs = certify_packed_rows(
        [row], [(3.0, 0.6, 0.2)], _canonical_dtype(None),
        _hashable_kwargs(dict(KW)))
    assert len(certs) == 1 and certs[0].failed
    # the checks tuple keeps the full CERT_CHECKS-ordered layout (every
    # consumer zips against it): unevaluated checks carry NaN residuals
    # and grade FAILED, the recompute check carries the status code
    assert tuple(c.name for c in certs[0].checks) == CERT_CHECKS
    by_name = {c.name: c for c in certs[0].checks}
    assert by_name["recompute"].residual == float(NONFINITE)
    assert np.isnan(by_name["euler"].residual)
    assert by_name["euler"].level == FAILED


def test_thresholds_scale_with_solver_config():
    loose = CertThresholds.for_solver(r_tol=1e-4)
    tight = CertThresholds.for_solver(r_tol=1e-10)
    assert loose.market_clearing > tight.market_clearing
    mixed = CertThresholds.for_solver(r_tol=1e-10, precision="mixed")
    assert mixed.market_clearing > tight.market_clearing
    # overrides thread through
    assert CertThresholds.for_solver(euler=0.5).euler == 0.5
    # grading: MARGINAL sits between tol and marginal_factor * tol
    thr = CertThresholds()
    assert thr.grade("euler", thr.euler * 0.5).level == CERTIFIED
    assert thr.grade("euler", thr.euler * 2.0).level == 1
    assert thr.grade("euler", thr.euler * 100.0).level == FAILED
    assert thr.grade("euler", float("nan")).level == FAILED
    # the recompute check has its own band: CONVERGED certifies, STALLED
    # is marginal, MAX_ITER/NONFINITE FAIL (a diverged recomputation must
    # never pass the certify-before-cache gate as MARGINAL)
    from aiyagari_hark_tpu.solver_health import (
        CONVERGED,
        MAX_ITER,
        STALLED,
    )

    assert thr.grade("recompute", float(CONVERGED)).level == CERTIFIED
    assert thr.grade("recompute", float(STALLED)).level == 1
    assert thr.grade("recompute", float(MAX_ITER)).level == FAILED
    assert thr.grade("recompute", float(NONFINITE)).level == FAILED


def test_sweep_certifies_all_cells_and_verdicts_stable():
    """12-cell acceptance at tier-1 scale: every cell CERTIFIED under
    default thresholds, and the verdict vector is identical across
    ``schedule=`` (bit-identical inputs) and ``precision=`` policies."""
    ref = run_table2_sweep(TWELVE.replace(certify=True), **KW)
    assert ref.cert_level is not None
    assert (ref.cert_level == CERTIFIED).all(), ref.cert_level
    assert ref.certify_wall_seconds > 0.0

    bal = run_table2_sweep(
        TWELVE.replace(certify=True, schedule="balanced"), **KW)
    assert np.array_equal(bal.cert_level, ref.cert_level)

    mixed = run_table2_sweep(TWELVE.replace(certify=True),
                             precision="mixed", **KW)
    assert (mixed.cert_level == CERTIFIED).all(), mixed.cert_level


# ---------------------------------------------------------------------------
# SDC spot-recheck.
# ---------------------------------------------------------------------------

def test_sdc_sample_deterministic_and_fraction_scaled():
    cells = np.asarray(TWELVE.cells())
    from aiyagari_hark_tpu.parallel.sweep import (
        _canonical_dtype,
        _hashable_kwargs,
    )

    dtype = _canonical_dtype(None)
    items = _hashable_kwargs(dict(KW))
    s1 = sdc_sample(cells, items, dtype, 0.25)
    assert len(s1) == 3            # ceil(0.25 * 12)
    assert np.array_equal(s1, sdc_sample(cells, items, dtype, 0.25))
    assert len(sdc_sample(cells, items, dtype, 1.0)) == 12
    assert len(sdc_sample(cells, items, dtype, 0.0)) == 0
    # a different solver configuration samples a different subset
    other = _hashable_kwargs({**KW, "a_count": 13})
    assert not np.array_equal(s1, sdc_sample(cells, other, dtype, 0.25))


def test_recheck_clean_run_no_suspects():
    res = run_table2_sweep(SMALL.replace(recheck_fraction=1.0), **KW)
    assert res.sdc_suspected is not None
    assert not res.sdc_suspected.any()
    assert res.recheck_wall_seconds > 0.0
    clean = run_table2_sweep(SMALL, **KW)
    np.testing.assert_array_equal(clean.r_star_pct, res.r_star_pct)


def test_injected_lane_corruption_detected_and_quarantined():
    """Acceptance: a post-solve bit flip on one lane is caught by the
    bitwise recheck, the cell is routed through the quarantine ladder
    (trusted re-solve), and every OTHER cell's bits are untouched."""
    clean = run_table2_sweep(SMALL, **KW)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        bad = run_table2_sweep(SMALL.replace(recheck_fraction=1.0),
                               inject_sdc={"cell": 1, "bit": 30}, **KW)
    assert any("silent data corruption" in str(x.message) for x in w)
    assert bad.sdc_suspected.sum() == 1 and bad.sdc_suspected[1]
    assert bad.retries[1] >= 1             # quarantine re-solved it
    assert np.isfinite(bad.r_star_pct[1])  # ...successfully
    others = [0, 2, 3]
    np.testing.assert_array_equal(clean.r_star_pct[others],
                                  bad.r_star_pct[others])
    np.testing.assert_array_equal(clean.status[others],
                                  bad.status[others])


def test_injected_corruption_without_recheck_goes_undetected():
    """recheck_fraction=0 disables the defense: the corruption sails
    through (the honest negative control for injected == detected)."""
    res = run_table2_sweep(SMALL, inject_sdc={"cell": 1, "bit": 30}, **KW)
    assert res.sdc_suspected is None


def test_suspected_cell_nan_masked_when_quarantine_off():
    """With quarantine=False no retry ladder runs: a suspected cell's
    KNOWN-corrupt values must still be NaN-masked (status NONFINITE),
    never kept as plausible finite numbers — the sidecar's NaN=failed
    warm-seed rule depends on it."""
    res = run_table2_sweep(SMALL.replace(recheck_fraction=1.0),
                           inject_sdc={"cell": 1, "bit": 30},
                           quarantine=False, **KW)
    assert res.sdc_suspected[1]
    assert res.status[1] == NONFINITE
    assert np.isnan(res.r_star_pct[1]) and np.isnan(res.capital[1])
    assert np.isfinite(res.r_star_pct[[0, 2, 3]]).all()


def test_recheck_skips_resumed_quarantine_outcomes(tmp_path):
    """A resumed ledger row holding a serial quarantine OUTCOME can never
    bitwise-match a fresh batched launch — the recheck must skip it
    loudly instead of reporting a false corruption alarm."""
    from aiyagari_hark_tpu.utils.resilience import SweepLedger
    from aiyagari_hark_tpu.verify.inject import _rewrite_npz_leaf

    ledger = str(tmp_path / "ledger.npz")
    try:
        with pytest.raises(Interrupted):
            run_table2_sweep(SMALL, resume_path=ledger,
                            inject_preempt={"after_bucket": 0,
                                            "mode": "flag"}, **KW)
    finally:
        clear_interrupt()
    # mark cell 2's row as a quarantine outcome (retried) in place — the
    # packed bytes (and so their checksum) are untouched, but the resume
    # must now treat the row as a serial-solve result the batched
    # executable cannot reproduce, and exclude it from the sample
    def mark(arr, value):
        arr = np.array(arr)
        arr[2] = value
        return arr

    _rewrite_npz_leaf(ledger, SweepLedger._fields.index("retried"),
                      lambda a: mark(a, True))
    _rewrite_npz_leaf(ledger, SweepLedger._fields.index("retries"),
                      lambda a: mark(a, 1))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        resumed = run_table2_sweep(SMALL.replace(recheck_fraction=1.0),
                                   resume_path=ledger, **KW)
    msgs = [str(x.message) for x in w]
    assert not resumed.sdc_suspected.any(), msgs
    assert any("skipping ledger-restored cell(s) [2]" in m for m in msgs)


# ---------------------------------------------------------------------------
# Checksummed artifact chain: sidecar + ledger.
# ---------------------------------------------------------------------------

def test_sidecar_checksum_roundtrip_and_corruption(tmp_path):
    from aiyagari_hark_tpu.verify.inject import _rewrite_npz_leaf
    from aiyagari_hark_tpu.utils.checkpoint import SweepSidecar

    path = str(tmp_path / "side.npz")
    save_sweep_sidecar(path, [[3.0, 0.6, 0.2]], [0.035], [11], [500],
                       [4000], [0], fingerprint=99)
    side = load_sweep_sidecar(path, 99)     # clean: verifies
    assert int(side.checksum) == side.content_checksum()

    # corrupt ONE root value in place, leaving the stored checksum —
    # the silent-corruption shape the checksum boundary exists to catch
    _rewrite_npz_leaf(path, SweepSidecar._fields.index("r_star"),
                      lambda r: r + 1e-9)
    with pytest.raises(IntegrityError):
        load_sweep_sidecar(path, 99)


def test_corrupt_sidecar_degrades_sweep_to_heuristic(tmp_path):
    """End to end: a sweep pointed at a corrupted sidecar warns and runs
    (heuristic work model) instead of trusting or crashing."""
    from aiyagari_hark_tpu.verify.inject import _rewrite_npz_leaf
    from aiyagari_hark_tpu.utils.checkpoint import SweepSidecar

    side = str(tmp_path / "side.npz")
    cfg = SMALL.replace(schedule="balanced", sidecar_path=side)
    first = run_table2_sweep(cfg, **KW)     # writes the sidecar
    _rewrite_npz_leaf(side, SweepSidecar._fields.index("dist_iters"),
                      lambda it: it + 1)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        again = run_table2_sweep(cfg, **KW)
    assert any("integrity" in str(x.message).lower() for x in w)
    np.testing.assert_array_equal(first.r_star_pct, again.r_star_pct)


def test_ledger_row_corruption_quarantined_on_resume(tmp_path):
    """Acceptance: flip one bit in a solved ledger row between interrupt
    and resume — the resume verifies checksums, quarantines exactly that
    cell (recompute), and the reassembled result is bit-identical to an
    uninterrupted run."""
    ledger = str(tmp_path / "ledger.npz")
    clean = run_table2_sweep(SMALL, **KW)
    try:
        with pytest.raises(Interrupted):
            run_table2_sweep(SMALL, resume_path=ledger,
                             inject_preempt={"after_bucket": 0,
                                             "mode": "flag"}, **KW)
    finally:
        clear_interrupt()
    assert os.path.exists(ledger)
    corrupt_ledger_row(ledger, cell=1, bit=21)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        resumed = run_table2_sweep(SMALL, resume_path=ledger, **KW)
    assert any("checksum verification failed" in str(x.message)
               for x in w)
    np.testing.assert_array_equal(clean.r_star_pct, resumed.r_star_pct)
    np.testing.assert_array_equal(clean.status, resumed.status)
    assert not os.path.exists(ledger)       # completed: deleted


def test_ledger_uncorrupted_resume_still_bit_identical(tmp_path):
    """Negative control: the checksum chain must not break the existing
    resume bit-identity contract."""
    ledger = str(tmp_path / "ledger.npz")
    clean = run_table2_sweep(SMALL, **KW)
    try:
        with pytest.raises(Interrupted):
            run_table2_sweep(SMALL, resume_path=ledger,
                             inject_preempt={"after_bucket": 0,
                                             "mode": "flag"}, **KW)
    finally:
        clear_interrupt()
    resumed = run_table2_sweep(SMALL, resume_path=ledger, **KW)
    np.testing.assert_array_equal(clean.r_star_pct, resumed.r_star_pct)
