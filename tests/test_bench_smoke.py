"""Fast bench smoke (ISSUE 2 satellite): the compile-cache contract and
the FLOP accounting the bench record is built from.

The load-bearing test runs a tiny 4-cell sweep twice in-process and
asserts the second launch performs ZERO XLA compiles (in-memory executable
cache), then drops the in-memory caches and asserts a third launch is
served entirely by the PERSISTENT compilation cache (zero cache misses) —
the contract that stops the benchmark trajectory from charging recompiles
to the solver."""

import jax
import numpy as np
import pytest

from aiyagari_hark_tpu.parallel.sweep import _batched_solver, run_table2_sweep
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.timing import (
    CompileCounter,
    flop_report,
    model_flops,
    peak_flops_per_chip,
)

# 4 cells, tiny grids: the smoke must cost seconds, not minutes.
SMOKE = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)


def test_second_sweep_launch_performs_zero_compiles():
    cache_dir = enable_compilation_cache()
    assert cache_dir, "compilation cache must be enabled for this test"
    # cache programs regardless of their compile time — the smoke's tiny
    # programs compile in well under the production 1 s threshold
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        with CompileCounter() as c1:
            first = run_table2_sweep(SMOKE, **KW)
        with CompileCounter() as c2:
            second = run_table2_sweep(SMOKE, **KW)
        # same process, same config: the jitted executable is reused —
        # not one compile request, cached or otherwise
        assert c2.compile_events == 0, c2.__dict__
        assert c2.cache_misses == 0
        assert np.array_equal(first.r_star_pct, second.r_star_pct)

        # drop the in-memory caches: the PERSISTENT cache must now serve
        # every compile request (zero XLA compiles, only cache hits)
        jax.clear_caches()
        _batched_solver.cache_clear()
        with CompileCounter() as c3:
            third = run_table2_sweep(SMOKE, **KW)
        assert c3.cache_misses == 0, c3.__dict__
        assert c3.cache_hits > 0
        assert np.array_equal(first.r_star_pct, third.r_star_pct)
    finally:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def test_batched_solver_dtype_alias_shares_cache_entry():
    """dtype=None vs the explicit default dtype must resolve to the SAME
    jitted closure — two entries meant two identical XLA compiles."""
    import jax.numpy as jnp

    from aiyagari_hark_tpu.parallel.sweep import _canonical_dtype

    fn_none = _batched_solver(_canonical_dtype(None))
    fn_expl = _batched_solver(_canonical_dtype(jnp.float64))
    assert fn_none is fn_expl


def test_model_flops_and_flop_report():
    """The shared FLOP accounting (moved to utils.timing): the dense
    distribution path dominates scatter by the D^2/D matvec ratio, the
    report rounds rate + MFU, and degenerate walls yield nulls instead of
    crashes — the fine-grid fields must never strand the record again."""
    egm_only = model_flops(10, 0, 32, 7, 500, dense_dist=True)
    assert egm_only == model_flops(10, 0, 32, 7, 500, dense_dist=False)
    dense = model_flops(0, 10, 32, 7, 500, dense_dist=True)
    scatter = model_flops(0, 10, 32, 7, 500, dense_dist=False)
    assert dense > 50 * scatter
    rep = flop_report(100, 1000, 2.0, 32, 7, 500, dense_dist=False,
                      backend="cpu")
    assert rep["flops_per_sec"] > 0 and rep["mfu_pct"] is None
    # provenance honesty bit (ISSUE 10 satellite): which source produced
    # the numerator — the analytic model by default, XLA when a measured
    # count is passed
    assert rep["flops_provenance"] == "analytic"
    measured = flop_report(100, 1000, 2.0, 32, 7, 500, dense_dist=False,
                           backend="cpu", measured_flops=1e9)
    assert measured["flops_provenance"] == "xla_cost_analysis"
    assert measured["flops_per_sec"] == round(1e9 / 2.0)
    nulls = {"flops_per_sec": None, "mfu_pct": None,
             "peak_flops_assumed": False, "flops_provenance": None}
    assert flop_report(1, 1, None, 32, 7, 500, False, "cpu") == nulls
    assert flop_report(1, 1, 0.0, 32, 7, 500, False, "cpu") == nulls


def test_peak_flops_value_assumed_contract():
    """ISSUE 4 satellite: the chip-peak table returns (value, assumed)
    instead of passing the unknown-TPU guess off as measured; CPU has no
    meaningful peak and is NOT 'assumed'."""
    peak = peak_flops_per_chip("cpu")
    assert peak.value is None and peak.assumed is False
    # on the CPU test backend a "tpu" query can't see a real device kind:
    # it must return the v5e class guess FLAGGED as assumed (and warn once)
    import warnings

    from aiyagari_hark_tpu.utils import timing

    timing._ASSUMED_PEAK_WARNED.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assumed = peak_flops_per_chip("tpu")
    assert assumed.value == 197e12 and assumed.assumed is True
    assert any("assum" in str(x.message) for x in w)
    # warn ONCE per unknown kind
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        peak_flops_per_chip("tpu")
    assert not w2


def test_bench_emits_scheduler_and_compile_fields():
    """The bench record contract this PR adds: post-scheduling skew and
    cold/warm compile attribution must be wired into the record builder
    (cheap source-level check — a full bench run is minutes)."""
    import inspect

    import bench

    src = inspect.getsource(bench)
    for fieldname in ("scheduled_iteration_skew", "compile_cold_s",
                      "compile_warm_s", "warm_inner_step_reduction_pct",
                      "fine_grid_cpu_flops_per_sec", "peak_flops_assumed"):
        assert fieldname in src, fieldname


def test_bench_emits_precision_ladder_fields():
    """ISSUE 5 record contract: the mixed-precision phase's fields must be
    wired into the record builder, and the lanes ladder must run scheduled
    and record the post-scheduling skew."""
    import inspect

    import bench

    src = inspect.getsource(bench._precision_ladder_metrics)
    for fieldname in ("precision_descent_steps", "precision_polish_steps",
                      "precision_polish_frac", "mixed_r_star_vs_ref_max_bp",
                      "mixed_speedup", "precision_escalations"):
        assert fieldname in src, fieldname
    assert "_precision_ladder_metrics(timer" in inspect.getsource(
        bench._run_bench)
    lanes_src = inspect.getsource(bench._lanes_scaling)
    assert 'schedule="balanced"' in lanes_src
    assert "iteration_skew_scheduled" in lanes_src


def test_record_null_sentinel_flags_stranded_fields():
    """ISSUE 5 satellite: a wall time present with its derived rate/MFU
    field null is the r05 stranding class — the checker must flag it, and
    must NOT flag the legitimate nulls (wall null too, or MFU on a
    backend with no chip peak)."""
    from bench import record_null_violations

    # the r05 last_tpu shape: dense failed, wall null → no violation
    assert record_null_violations(
        {"backend": "tpu", "fine_grid_wall_s": None,
         "fine_grid_flops_per_sec": None, "fine_grid_mfu_pct": None}) == []
    # CPU record: mfu legitimately null (no peak), flops present → clean
    assert record_null_violations(
        {"backend": "cpu", "fine_grid_wall_s": 1.3,
         "fine_grid_flops_per_sec": 5, "fine_grid_mfu_pct": None}) == []
    # the bug class: wall present, derived null
    bad = record_null_violations(
        {"backend": "tpu", "fine_grid_wall_s": 1.3,
         "fine_grid_flops_per_sec": None, "fine_grid_mfu_pct": 0.1})
    assert ("fine_grid_wall_s", "fine_grid_flops_per_sec") in bad
    bad_mfu = record_null_violations(
        {"backend": "axon", "fine_grid_lanes4_wall_s": 2.0,
         "fine_grid_lanes4_cells_per_sec": 2.0,
         "fine_grid_lanes4_mfu_pct": None})
    assert ("fine_grid_lanes4_wall_s", "fine_grid_lanes4_mfu_pct") in bad_mfu
    # the checker is wired into the record builder, and a failed fine-grid
    # attempt no longer claims fine_grid_method
    import inspect

    import bench

    assert "record_null_violations(record)" in inspect.getsource(
        bench._run_bench)
    assert "fine_grid_failed_method" in inspect.getsource(
        bench._fine_grid_metrics)


def test_serve_metrics_emit_precision_fields():
    from aiyagari_hark_tpu.serve import ServeMetrics

    m = ServeMetrics()
    snap = m.snapshot()
    assert snap["serve_polish_frac"] is None      # no solves yet
    m.record_phases(300, 100, 1)
    snap = m.snapshot()
    assert snap["serve_descent_steps"] == 300
    assert snap["serve_polish_steps"] == 100
    assert snap["serve_polish_frac"] == 0.25
    assert snap["serve_precision_escalations"] == 1


def test_bench_serve_smoke_fields_wired():
    """--serve-smoke record contract (ISSUE 4 satellite): the serving
    fields must be produced by the metrics snapshot and the smoke body."""
    import inspect

    import bench
    from aiyagari_hark_tpu.serve import ServeMetrics

    snap = ServeMetrics().snapshot()
    for fieldname in ("serve_hit_rate", "serve_p50_ms", "serve_p95_ms",
                      "serve_batch_occupancy", "serve_compiles"):
        assert fieldname in snap, fieldname
    src = inspect.getsource(bench._serve_smoke)
    for fieldname in ("serve_hit_replay_compiles", "serve_hit_under_1ms",
                      "serve_warm_evals_reduction_pct",
                      "peak_flops_assumed"):
        assert fieldname in src, fieldname


@pytest.mark.slow
def test_serve_smoke_end_to_end():
    """bench._serve_smoke() against the real (tiny) 12-cell workload:
    the ISSUE 4 acceptance numbers — sub-ms exact hits, zero compiles
    across the shuffled replay, warm neighbor replay strictly cheaper
    than cold."""
    import bench

    rec = bench._serve_smoke()
    assert rec["serve_hit_replay_compiles"] == 0
    assert rec["serve_hit_under_1ms"] is True
    assert rec["serve_failures"] == 0
    assert rec["serve_warm_bisect_evals"] < rec["serve_cold_bisect_evals"]
    assert rec["serve_hit_rate"] == pytest.approx(1.0 / 3.0, abs=0.01)
    assert rec["serve_batch_occupancy"] == 1.0


@pytest.mark.slow
def test_warm_scheduled_metrics_end_to_end(tmp_path, monkeypatch):
    """bench._warm_scheduled_metrics against a real (tiny) sweep."""
    import bench

    from aiyagari_hark_tpu.utils.timing import PhaseTimer

    monkeypatch.setattr(bench, "_repo_dir", lambda: str(tmp_path))
    # the bench hands the function its default-lattice headline result
    base = run_table2_sweep(SweepConfig(), **KW)
    out = bench._warm_scheduled_metrics(PhaseTimer(), dict(KW), base)
    assert "warm_sweep_wall_s" in out
    assert out.get("warm_sweep_error") is None, out
    assert out["warm_vs_base_max_bp"] < 0.5
