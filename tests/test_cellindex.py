"""CellIndex bitwise contract + the store's neighbor seam (ISSUE 17).

The grid-bucket index is an OPTIMIZATION, never a semantics change: it
must return EXACTLY what the linear scan returns — same keys, same
float64 distances, same tie order (metadata-dict insertion order) —
across puts, value refreshes, removals, evictions, restart rebuilds,
and every registered scenario's CellSpace normalization.  The reference
model here is deliberately dumb: a plain insertion-ordered list ranked
by ``linear_nearest_k``, the same comparator ``bench.py`` speed-grades
the index against.
"""

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import ObsConfig, build_obs, read_journal
from aiyagari_hark_tpu.scenarios import get_scenario, scenario_names
from aiyagari_hark_tpu.serve import (
    CellIndex,
    SolutionStore,
    linear_nearest_k,
    make_solution,
)
from aiyagari_hark_tpu.solver_health import CONVERGED

GROUP = 7


def entry(key, cell=(3.0, 0.6, 0.2), r_star=0.035, group=GROUP,
          cert_level=-1):
    packed = np.asarray([r_star, 5.0, 0.9, 11.0, 500.0, 4000.0,
                         float(CONVERGED), 0.0, 4500.0, 0.0])
    return make_solution(cell, packed, group, key, cert_level=cert_level)


# ---------------------------------------------------------------------------
# Reference model: an insertion-ordered item list + the linear comparator.
# ---------------------------------------------------------------------------

class _Model:
    """Mirror of the metadata-dict insertion-order semantics CellIndex
    pins: a value refresh of a live key at the SAME cell keeps its
    position (dict update); a changed cell or a remove + re-add moves
    the key to the tail (re-insertion)."""

    def __init__(self):
        self.items = []          # [key, cell, r_star, cert]

    def add(self, key, cell, r_star, cert):
        for it in self.items:
            if it[0] == key:
                if it[1] == cell:
                    it[2], it[3] = r_star, cert
                    return
                self.items.remove(it)
                break
        self.items.append([key, cell, r_star, cert])

    def remove(self, key):
        self.items = [it for it in self.items if it[0] != key]

    def nearest_k(self, cell, k, scale, require_certified=False):
        rows = [(key, c) for key, c, r, cert in self.items
                if np.isfinite(r) and (not require_certified
                                       or cert >= 0)]
        if not rows:
            return []
        mat = np.asarray([c for _, c in rows], dtype=np.float64)
        hits = linear_nearest_k(cell, mat, np.arange(len(rows)), k, scale)
        return [(rows[i][0], d) for i, d in hits]


def _lattice_cell(rng, scale, n_ticks=5, tick=0.5):
    """Cells snapped to a coarse lattice IN NORMALIZED UNITS so exact
    L1-distance ties are common — the tie-order contract must actually
    be exercised, not dodged by generic floats."""
    return tuple(float(rng.integers(0, n_ticks)) * tick * s
                 for s in scale)


@pytest.mark.parametrize("scenario", sorted(scenario_names()))
def test_index_bitwise_matches_linear_scan(scenario):
    space = get_scenario(scenario).cells
    scale = space.scale
    rng = np.random.default_rng(sum(map(ord, scenario)))
    idx = CellIndex()
    model = _Model()
    keypool = list(range(40))
    for step in range(400):
        if rng.random() < 0.75 or not model.items:
            key = int(rng.choice(keypool))
            cell = _lattice_cell(rng, scale)
            r = [0.03, 0.041, float("nan")][int(rng.integers(0, 3))
                                            if rng.random() < 0.15 else
                                            int(rng.integers(0, 2))]
            cert = int(rng.integers(-1, 2))
            idx.add(key, cell, GROUP, r, cert)
            model.add(key, cell, r, cert)
        else:
            key = model.items[int(rng.integers(0, len(model.items)))][0]
            idx.remove(key, GROUP)
            model.remove(key)
        if step % 5 == 0:
            q = (_lattice_cell(rng, scale) if rng.random() < 0.5
                 else tuple(float(rng.uniform(0.0, 2.5)) * s
                            for s in scale))
            for k in (1, 2, 6, len(model.items) + 3, None):
                for rc in (False, True):
                    got = idx.nearest_k(q, GROUP, k, scale=scale,
                                        require_certified=rc)
                    want = model.nearest_k(q, k, scale, rc)
                    assert got == want, (scenario, step, k, rc)
    assert len(idx) == len(model.items)
    assert idx.group_size(GROUP) == len(model.items)


def test_index_empty_and_unknown_group():
    idx = CellIndex()
    scale = (1.0, 1.0, 1.0)
    assert idx.nearest_k((0.0, 0.0, 0.0), 3, 1, scale=scale) == []
    idx.add(1, (0.5, 0.5, 0.5), 3, 0.03, 0)
    idx.remove(1, 3)
    assert idx.nearest_k((0.0, 0.0, 0.0), 3, 1, scale=scale) == []
    assert len(idx) == 0


def test_index_rebuild_reasons_and_counter():
    """first_query on the lazy build; rewidth after 4x growth;
    scale_change when a different normalization arrives — each invokes
    on_rebuild so the store can journal INDEX_REBUILD."""
    seen = []
    idx = CellIndex(on_rebuild=lambda g, n, reason: seen.append(reason))
    rng = np.random.default_rng(11)
    scale = (1.0, 1.0, 1.0)
    for i in range(70):
        idx.add(i, tuple(rng.uniform(0.0, 4.0, 3)), 0, 0.03, 0)
    idx.nearest_k((1.0, 1.0, 1.0), 0, 2, scale=scale)
    assert seen == ["first_query"]
    for i in range(70, 70 + 70 * 4 + 8):
        idx.add(i, tuple(rng.uniform(0.0, 4.0, 3)), 0, 0.03, 0)
    idx.nearest_k((1.0, 1.0, 1.0), 0, 2, scale=scale)
    assert seen == ["first_query", "rewidth"]
    idx.nearest_k((1.0, 1.0, 1.0), 0, 2, scale=(2.0, 1.0, 1.0))
    assert seen == ["first_query", "rewidth", "scale_change"]
    assert idx.rebuilds == 3


# ---------------------------------------------------------------------------
# The store seam: grid-indexed and linear stores answer identically.
# ---------------------------------------------------------------------------

def _tie_cells():
    """A donor set with exact normalized-L1 ties around (3.0, 0.6, 0.2)
    under the default Aiyagari scale — plus far and off-axis points."""
    return [
        (3.0, 0.6, 0.2),
        (3.5, 0.6, 0.2), (2.5, 0.6, 0.2),       # tie pair (d = 0.1)
        (3.0, 0.65, 0.2), (3.0, 0.55, 0.2),     # tie pair (d = 0.1)
        (4.0, 0.9, 0.2), (1.5, 0.0, 0.2),
        (3.5, 0.65, 0.2),
    ]


def _pair_stores(**kw):
    return (SolutionStore(index="grid", **kw),
            SolutionStore(index="linear", **kw))


def _strip(hits):
    return [(k, d) for k, _, d in hits]


def test_store_neighbors_grid_equals_linear():
    g, lin = _pair_stores(capacity=32)
    for i, c in enumerate(_tie_cells()):
        cert = 0 if i % 2 == 0 else -1
        for s in (g, lin):
            s.put(entry(100 + i, cell=c, cert_level=cert))
    queries = [(3.0, 0.6, 0.2), (3.1, 0.62, 0.2), (0.0, 0.0, 0.0),
               (3.25, 0.6, 0.2)]
    for q in queries:
        for k in (1, 2, 5, None):
            for rc in (False, True):
                assert (_strip(g.neighbors(q, GROUP, k,
                                           require_certified=rc))
                        == _strip(lin.neighbors(q, GROUP, k,
                                                require_certified=rc)))
        assert g.nominate(q, GROUP, 0.14, 1e-5) \
            == lin.nominate(q, GROUP, 0.14, 1e-5)
        assert g.nearest(q, GROUP) == lin.nearest(q, GROUP)
        assert g.nearest(q, GROUP, require_certified=True) \
            == lin.nearest(q, GROUP, require_certified=True)


def test_store_neighbors_agree_through_eviction():
    """Memory-only eviction forgets entries; the index must track the
    deletions and keep answering exactly like the linear fallback."""
    g, lin = _pair_stores(capacity=3)
    for i, c in enumerate(_tie_cells()):             # 8 puts, 3 survive
        for s in (g, lin):
            s.put(entry(200 + i, cell=c))
    q = (3.0, 0.6, 0.2)
    got = _strip(g.neighbors(q, GROUP, None))
    assert got == _strip(lin.neighbors(q, GROUP, None))
    assert len(got) == 3
    assert g.index_stats()["index_entries"] == 3
    assert lin.index_stats()["index_kind"] == "linear"


def test_store_index_kind_validated():
    with pytest.raises(ValueError):
        SolutionStore(capacity=4, index="btree")


def test_group_matrix_cache_is_behavior_identical():
    """ISSUE 17 satellite: the linear path's cached per-group cell
    matrix must never change an answer — a long-lived store (cache warm
    across puts/evictions/refreshes) answers exactly like a fresh store
    replaying the same mutation sequence cold."""
    live = SolutionStore(capacity=3, index="linear")
    history = []
    q = (3.0, 0.6, 0.2)
    for i, c in enumerate(_tie_cells()):
        live.put(entry(300 + i, cell=c))
        history.append((300 + i, c, 0.035))
        if i == 4:                                   # refresh key 302
            live.put(entry(302, cell=_tie_cells()[2], r_star=0.05))
            history.append((302, _tie_cells()[2], 0.05))
        # query NOW so the cache is built, then mutated, repeatedly
        fresh = SolutionStore(capacity=3, index="linear")
        for kk, cc, rr in history:
            fresh.put(entry(kk, cell=cc, r_star=rr))
        for k in (1, 2, None):
            assert _strip(live.neighbors(q, GROUP, k)) \
                == _strip(fresh.neighbors(q, GROUP, k))
        assert live.nominate(q, GROUP, 0.14, 1e-5) \
            == fresh.nominate(q, GROUP, 0.14, 1e-5)


# ---------------------------------------------------------------------------
# Restart: the reborn store's index rebuild is journaled and bitwise.
# ---------------------------------------------------------------------------

def test_restart_rebuild_bitwise_and_journaled(tmp_path):
    d = str(tmp_path / "tier")
    jp = str(tmp_path / "events.jsonl")
    first = SolutionStore(capacity=8, disk_path=d)
    for i, c in enumerate(_tie_cells()):
        first.put(entry(400 + i, cell=c, cert_level=0 if i < 4 else -1))
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    reborn_g = SolutionStore(capacity=8, disk_path=d, obs=obs)
    reborn_l = SolutionStore(capacity=8, disk_path=d, index="linear")
    for q in [(3.0, 0.6, 0.2), (3.1, 0.62, 0.2), (2.75, 0.6, 0.2)]:
        for k in (1, 3, None):
            for rc in (False, True):
                assert (_strip(reborn_g.neighbors(
                            q, GROUP, k, require_certified=rc))
                        == _strip(reborn_l.neighbors(
                            q, GROUP, k, require_certified=rc)))
    assert reborn_g.index_stats()["index_entries"] == len(_tie_cells())
    obs.close()
    ev = read_journal(jp, event="INDEX_REBUILD")
    assert ev and ev[0]["reason"] == "restart"
    assert ev[0]["entries"] == len(_tie_cells())
