"""Serving-path integrity (ISSUE 6, DESIGN §9): deadlines at batch
seams, checksummed store tiers with evict-and-delete, and the
certify-before-cache gate (no FAILED-certificate solution is ever
written to the SolutionStore)."""

import glob
import os
import warnings

import numpy as np
import pytest

from aiyagari_hark_tpu.serve import (
    CertificationFailed,
    DeadlineExceeded,
    EquilibriumService,
    SolutionStore,
    make_query,
    make_solution,
)
from aiyagari_hark_tpu.solver_health import DEADLINE_EXCEEDED, is_failure
from aiyagari_hark_tpu.verify import CERTIFIED, corrupt_store_entry
from aiyagari_hark_tpu.verify.inject import flip_row_bit

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
          max_bisect=24)


def _manual_service(**kwargs):
    return EquilibriumService(start_worker=False, max_batch=4,
                              ladder=(1, 2, 4), **kwargs)


# ---------------------------------------------------------------------------
# Deadlines (the SLO satellite).
# ---------------------------------------------------------------------------

def test_expired_query_fails_typed_at_batch_seam():
    t = [0.0]
    svc = _manual_service(clock=lambda: t[0])
    expired = svc.submit(make_query(3.0, 0.6, **KW), deadline=0.5)
    live = svc.submit(make_query(1.0, 0.3, **KW), deadline=100.0)
    nodeadline = svc.submit(make_query(5.0, 0.9, **KW))
    t[0] = 1.0
    svc.flush()
    with pytest.raises(DeadlineExceeded) as ei:
        expired.result(0)
    assert ei.value.status == DEADLINE_EXCEEDED
    assert is_failure(ei.value.status)          # uncertified by definition
    assert ei.value.waited_s == pytest.approx(1.0)
    # batchmates are untouched: the live and no-deadline queries solved
    assert live.result(0).r_star != 0.0
    assert nodeadline.result(0).r_star != 0.0
    snap = svc.metrics.snapshot()
    assert snap["serve_deadline_expirations"] == 1
    assert snap["serve_failures"] == 0          # expiry is not a solve failure
    svc.close()


def test_expired_query_never_launches_or_caches():
    t = [0.0]
    svc = _manual_service(clock=lambda: t[0])
    fut = svc.submit(make_query(3.0, 0.6, **KW), deadline=0.5)
    t[0] = 1.0
    svc.flush()
    with pytest.raises(DeadlineExceeded):
        fut.result(0)
    assert svc.store.known() == 0               # nothing was solved
    assert svc.metrics.snapshot()["serve_batches"] == 0
    svc.close()


def test_deadline_resolves_hit_before_expiry_check():
    """An exact hit resolves at submit — a deadline can never expire it."""
    t = [0.0]
    svc = _manual_service(clock=lambda: t[0])
    svc.query(3.0, 0.6, **KW)
    fut = svc.submit(make_query(3.0, 0.6, **KW), deadline=0.0)
    assert fut.done() and fut.result(0).path == "hit"
    svc.close()


# ---------------------------------------------------------------------------
# Store checksum chain: evict, delete, count, re-solve.
# ---------------------------------------------------------------------------

def test_perturbed_disk_entry_evicted_deleted_counted(tmp_path):
    d = str(tmp_path / "store")
    svc = _manual_service(disk_path=d)
    first = svc.query(3.0, 0.6, **KW)
    svc.close()

    path = corrupt_store_entry(d, mode="perturb", amplitude=1e-3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        svc2 = _manual_service(disk_path=d)
    msgs = [str(x.message) for x in w]
    assert any("evicting corrupt entry" in m for m in msgs)
    assert not os.path.exists(path)             # deleted: cannot re-degrade
    assert svc2.store.integrity_counts()["store_corrupt_evictions"] == 1
    # a THIRD process sees a clean (empty) store: no repeat warnings
    again = svc2.query(3.0, 0.6, **KW)
    assert again.path == "cold"                 # re-solved, never served
    assert again.r_star == first.r_star         # ...and correct
    assert svc2.metrics.snapshot()["store_corrupt_evictions"] == 1
    svc2.close()
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        svc3 = _manual_service(disk_path=d)
    assert not any("evicting" in str(x.message) for x in w2)
    svc3.close()


@pytest.mark.parametrize("mode", ["truncate", "zero"])
def test_unreadable_disk_entry_evicted_at_index_load(tmp_path, mode):
    d = str(tmp_path / "store")
    svc = _manual_service(disk_path=d)
    svc.query(3.0, 0.6, **KW)
    svc.close()
    corrupt_store_entry(d, mode=mode)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        store = SolutionStore(capacity=4, disk_path=d)
    assert any("evicting corrupt entry" in str(x.message) for x in w)
    assert store.known() == 0
    assert store.integrity_counts()["store_corrupt_evictions"] == 1
    assert glob.glob(os.path.join(d, "sol_*.npz")) == []


def test_memory_tier_corruption_evicted_on_first_get():
    """A bit flip in the MEMORY tier before the residency's first
    verification is caught: get() verifies once per residency (the
    ISSUE 15 memoization) and reports a miss instead of serving it."""
    store = SolutionStore(capacity=4)
    row = np.asarray([0.035, 5.0, 0.9, 11, 500, 4000, 0, 0, 4500, 0],
                     dtype=np.float64)
    store.put(make_solution((3.0, 0.6, 0.2), row, group=7, key=1))
    # corrupt the cached object's bytes in place BEFORE the first get
    # (make_solution aliases the caller's array, so `row` reaches it)
    row[:] = flip_row_bit(row, field=0, bit=18)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert store.get(1) is None
    assert any("memory tier" in str(x.message) for x in w)
    assert store.integrity_counts()["store_corrupt_evictions"] == 1


def test_memory_tier_corruption_recovers_from_healthy_disk_copy(tmp_path):
    """An in-RAM flip must NOT destroy the (independently verified) disk
    copy: the first-get verification falls through, re-verifies the
    file, and serves it — one transient memory corruption is not a
    permanent cache loss."""
    store = SolutionStore(capacity=4, disk_path=str(tmp_path / "s"))
    row = np.asarray([0.035, 5.0, 0.9, 11, 500, 4000, 0, 0, 4500, 0],
                     dtype=np.float64)
    pristine = row.copy()   # make_solution aliases the caller's array —
    #                         the in-place flip below reaches `row` too
    store.put(make_solution((3.0, 0.6, 0.2), row, group=7, key=1))
    row[:] = flip_row_bit(row, field=0, bit=18)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        recovered = store.get(1)
    assert any("retrying the disk tier" in str(x.message) for x in w)
    assert recovered is not None
    assert np.array_equal(np.asarray(recovered.packed), pristine)
    assert store.integrity_counts()["store_corrupt_evictions"] == 1
    # and the disk file survived
    assert store.get(1) is not None


def test_checksum_memoized_after_residency_disk_still_caught(tmp_path):
    """The ISSUE 15 memoization pin, both halves.  (a) A mutation AFTER
    a residency's verified first get is out of the threat model: later
    memory hits serve without re-hashing (that is the perf contract —
    the hot path pays the hash once per residency, not per hit).  (b)
    The DISK tier's corrupt-eviction semantics are unchanged: the same
    entry's file, corrupted on disk, is still caught and evicted at
    every load boundary (promotion and restart)."""
    d = str(tmp_path / "s")
    store = SolutionStore(capacity=4, disk_path=d)
    row = np.asarray([0.035, 5.0, 0.9, 11, 500, 4000, 0, 0, 4500, 0],
                     dtype=np.float64)
    store.put(make_solution((3.0, 0.6, 0.2), row, group=7, key=1))
    first = store.get(1)
    assert first is not None            # first get verified the bytes
    # (a) mutate after residency: served without detection (memoized)
    first.packed[:] = flip_row_bit(first.packed, field=0, bit=18)
    assert store.get(1) is not None
    assert store.integrity_counts()["store_corrupt_evictions"] == 0
    # (b) disk corruption is still caught: evict the memory copy by
    # filling the LRU, corrupt the FILE, and re-get -> promotion
    # verifies, evicts, deletes
    for k in range(2, 6):
        store.put(make_solution((1.0, 0.0, 0.2), row.copy(), group=7,
                                key=k))
    assert 1 not in store.mem_keys()
    corrupt_store_entry(d, key=1, mode="perturb")
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert store.get(1) is None
    assert store.integrity_counts()["store_corrupt_evictions"] == 1
    # and the restart-time load boundary catches one the same way
    corrupt_store_entry(d, key=2, mode="perturb")
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        store2 = SolutionStore(capacity=4, disk_path=d)
    assert store2.get(2) is None
    assert store2.integrity_counts()["store_corrupt_evictions"] == 1


def test_corrupted_entry_on_get_path_deleted(tmp_path):
    """Disk corruption AFTER the index was built (rot between index load
    and get): the get path verifies, evicts, deletes."""
    d = str(tmp_path / "store")
    svc = _manual_service(disk_path=d)
    svc.query(3.0, 0.6, **KW)
    svc.close()
    svc2 = _manual_service(disk_path=d)        # index load verifies: clean
    path = corrupt_store_entry(d, mode="perturb", amplitude=1e-3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        r = svc2.query(3.0, 0.6, **KW)
    assert any("evicting corrupt entry" in str(x.message) for x in w)
    assert r.path == "cold"                     # re-solved, never served
    assert svc2.store.integrity_counts()["store_corrupt_evictions"] == 1
    # the re-solve re-cached a CLEAN entry at the same address: a third
    # process loads it without any eviction warning
    with warnings.catch_warnings(record=True) as w2:
        warnings.simplefilter("always")
        svc3 = _manual_service(disk_path=d)
    assert not any("evicting" in str(x.message) for x in w2)
    assert svc3.query(3.0, 0.6, **KW).path == "hit"
    svc3.close()
    svc2.close()


# ---------------------------------------------------------------------------
# certify_before_cache (the acceptance property).
# ---------------------------------------------------------------------------

def test_certified_cold_miss_cached_with_level(tmp_path):
    svc = _manual_service(certify_before_cache=True,
                          disk_path=str(tmp_path / "s"))
    r = svc.query(3.0, 0.6, **KW)
    assert r.path == "cold" and r.cert_level == CERTIFIED
    hit = svc.query(3.0, 0.6, **KW)
    assert hit.path == "hit" and hit.cert_level == CERTIFIED
    snap = svc.metrics.snapshot()
    assert snap["serve_certified"] == 1
    assert snap["serve_failed_certificates"] == 0
    svc.close()
    # the certificate level survives the disk tier and a restart
    svc2 = _manual_service(disk_path=str(tmp_path / "s"))
    assert svc2.query(3.0, 0.6, **KW).cert_level == CERTIFIED
    svc2.close()


def test_failed_certificate_never_written_to_store():
    """ISSUE 6 acceptance: with certify_before_cache on, an injected
    post-solve lane perturbation FAILS certification, the future raises
    typed, and the store never sees the solution; batchmates and the
    next clean solve are unaffected."""
    svc = _manual_service(
        certify_before_cache=True,
        inject_corrupt_lane={"at_launch": 0, "lane": 0, "field": 0,
                             "amplitude": 3e-3})
    corrupt = svc.submit(make_query(3.0, 0.6, **KW))
    mate = svc.submit(make_query(1.0, 0.3, **KW))
    svc.flush()
    with pytest.raises(CertificationFailed) as ei:
        corrupt.result(0)
    assert ei.value.certificate.failed
    assert ei.value.cell == (3.0, 0.6, 0.2)
    # the batchmate solved, certified, and cached normally
    assert mate.result(0).cert_level == CERTIFIED
    assert svc.store.known() == 1               # ONLY the clean batchmate
    assert svc.store.get(ei.value.key) is None  # the corrupt one: never
    snap = svc.metrics.snapshot()
    assert snap["serve_failed_certificates"] == 1
    assert snap["serve_certified"] == 1
    # launch 1 (no injection): the same query now solves (near-hit warm
    # start from the cached batchmate is fine), certifies, caches
    clean = svc.query(3.0, 0.6, **KW)
    assert clean.path in ("cold", "near") and clean.cert_level == CERTIFIED
    assert svc.store.known() == 2
    svc.close()


def test_shared_metrics_sums_eviction_counts_across_stores(tmp_path):
    """One ServeMetrics shared by several services reports the SUM of
    their stores' corruption evictions — a later attach must not drop an
    earlier store's counter."""
    from aiyagari_hark_tpu.serve import ServeMetrics

    metrics = ServeMetrics()
    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    for d in (a, b):
        svc = _manual_service(disk_path=d)
        svc.query(3.0, 0.6, **KW)
        svc.close()
        corrupt_store_entry(d, mode="perturb")
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        svc_a = _manual_service(disk_path=a, metrics=metrics)
        svc_b = _manual_service(disk_path=b, metrics=metrics)
    assert metrics.snapshot()["store_corrupt_evictions"] == 2
    svc_a.close()
    svc_b.close()


def test_uncertified_service_leaves_level_unset():
    svc = _manual_service()
    r = svc.query(3.0, 0.6, **KW)
    assert r.cert_level is None
    hit = svc.query(3.0, 0.6, **KW)
    assert hit.path == "hit" and hit.cert_level is None
    svc.close()
