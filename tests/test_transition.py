"""Perfect-foresight transition dynamics (models/transition.py).

Oracles: exact steady-state invariance (a transition that starts at the
stationary equilibrium with no shock must stay there), and the textbook
impulse response to a transitory TFP shock (capital hump, reversion to the
stationary level)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.models.transition import solve_transition

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


ALPHA, DELTA, BETA, CRRA = 0.36, 0.08, 0.96, 2.0


@pytest.fixture(scope="module")
def steady_state():
    model = build_simple_model(labor_states=5, a_count=40, dist_count=300)
    eq = solve_bisection_equilibrium(model, BETA, CRRA, ALPHA, DELTA)
    return model, eq


def test_steady_state_is_invariant(steady_state):
    """No shock + stationary initial distribution => the path IS the
    steady state, to solver tolerance, at every horizon point."""
    model, eq = steady_state
    res = solve_transition(model, BETA, CRRA, ALPHA, DELTA,
                           init_dist=eq.distribution,
                           terminal_policy=eq.policy,
                           k_terminal=eq.capital, horizon=60)
    assert bool(res.converged)
    k = np.asarray(res.k_path)
    np.testing.assert_allclose(k, float(eq.capital), rtol=2e-3)
    np.testing.assert_allclose(np.asarray(res.r_path), float(eq.r_star),
                               atol=2e-4)


def test_transitory_tfp_shock_impulse_response(steady_state):
    """A 2% TFP shock decaying at 0.8/period: on impact the rental rate
    jumps and households save the windfall; capital humps above the
    stationary level, then everything reverts."""
    model, eq = steady_state
    horizon = 120
    prod = 1.0 + 0.02 * 0.8 ** jnp.arange(horizon)
    res = solve_transition(model, BETA, CRRA, ALPHA, DELTA,
                           init_dist=eq.distribution,
                           terminal_policy=eq.policy,
                           k_terminal=eq.capital, horizon=horizon,
                           prod_path=prod)
    assert bool(res.converged)
    k = np.asarray(res.k_path)
    r = np.asarray(res.r_path)
    k_ss = float(eq.capital)
    # impact: r above its stationary level (TFP raises the MPK)
    assert r[0] > float(eq.r_star) + 1e-4
    # capital is predetermined on impact, then accumulates above SS
    np.testing.assert_allclose(k[0], k_ss, rtol=1e-6)
    assert k[1:40].max() > k_ss * 1.002
    # hump shape: the peak is interior
    peak = int(k.argmax())
    assert 1 < peak < horizon - 10
    # reversion: the tail is back at the stationary level
    np.testing.assert_allclose(k[-1], k_ss, rtol=5e-3)
    # aggregate consumption rises during the boom
    c = np.asarray(res.c_agg_path)
    assert c[:20].mean() > c[-20:].mean() * 1.001


def test_transition_welfare_no_shock_is_zero(steady_state):
    """Living through a no-shock 'transition' that starts at the
    stationary equilibrium is worth exactly nothing: the backward value
    recursion along flat prices must reproduce the stationary value, so
    the consumption equivalent is ~0 (both sides share the same value
    numerics, so approximation errors cancel)."""
    from aiyagari_hark_tpu.models.transition import transition_welfare

    model, eq = steady_state
    res = solve_transition(model, BETA, CRRA, ALPHA, DELTA,
                           init_dist=eq.distribution,
                           terminal_policy=eq.policy,
                           k_terminal=eq.capital, horizon=60)
    tw = transition_welfare(model, BETA, CRRA, eq.distribution,
                            eq.policy, res.r_path, res.w_path)
    assert abs(float(tw.ce)) < 1e-4
    # and nobody's individual CE moves either (populated cells only —
    # empty top-of-grid cells never entered the aggregate)
    mass = np.asarray(eq.distribution) > 1e-9
    assert np.abs(np.asarray(tw.ce_by_cell))[mass].max() < 5e-4


def _shock_welfare(steady_state, size, horizon=100):
    from aiyagari_hark_tpu.models.transition import transition_welfare

    model, eq = steady_state
    prod = 1.0 + size * 0.8 ** jnp.arange(horizon)
    res = solve_transition(model, BETA, CRRA, ALPHA, DELTA,
                           init_dist=eq.distribution,
                           terminal_policy=eq.policy,
                           k_terminal=eq.capital, horizon=horizon,
                           prod_path=prod)
    assert bool(res.converged)
    return transition_welfare(model, BETA, CRRA, eq.distribution,
                              eq.policy, res.r_path, res.w_path)


@pytest.fixture(scope="module")
def tfp_shock_2pct(steady_state):
    """The 2% impulse's welfare, shared by the size and incidence
    tests (the transition + value recursion is the expensive part)."""
    return _shock_welfare(steady_state, 0.02)


def test_transition_welfare_of_tfp_shock(steady_state, tfp_shock_2pct):
    """A beneficial transitory TFP impulse has positive, small, and
    monotone-in-size consumption-equivalent value."""
    ce2 = float(tfp_shock_2pct.ce)
    ce4 = float(_shock_welfare(steady_state, 0.04).ce)
    assert 0.0 < ce2 < 0.02        # a 5-quarter-ish 2% shock is worth
    assert ce4 > 1.8 * ce2         # <2% permanent consumption, ~linear


def test_tfp_shock_welfare_incidence(steady_state, tfp_shock_2pct):
    """Distributional incidence of a beneficial TFP impulse: every
    populated household type gains (wages and returns both rise on
    impact), and the gains are NOT uniform — the aggregate CE hides
    real dispersion across the wealth distribution."""
    model, eq = steady_state
    tw = tfp_shock_2pct
    ce = np.asarray(tw.ce_by_cell)
    mass = np.asarray(eq.distribution) > 1e-9
    assert (ce[mass] > -1e-5).all()            # nobody loses
    spread = ce[mass].max() - ce[mass].min()
    assert spread > 0.1 * abs(float(tw.ce))    # real dispersion
    # population-weighted mean CE is consistent with the aggregate CE
    mean_ce = float(np.sum(np.asarray(eq.distribution) * ce))
    np.testing.assert_allclose(mean_ce, float(tw.ce),
                               atol=0.3 * abs(float(tw.ce)))


def test_transition_is_jittable(steady_state):
    model, eq = steady_state
    f = jax.jit(lambda d: solve_transition(
        model, BETA, CRRA, ALPHA, DELTA, init_dist=d,
        terminal_policy=eq.policy, k_terminal=eq.capital, horizon=40))
    res = f(eq.distribution)
    assert np.isfinite(np.asarray(res.k_path)).all()
