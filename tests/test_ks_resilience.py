"""KS outer-loop resilience (ISSUE 3): graceful preemption at iteration
boundaries and torn-write recovery of the checkpoint/sidecar pair.

``ks_solver`` documents the sidecar-before-checkpoint write order and the
iteration-tag mismatch degradation; until this module no test actually
killed a run between the two writes (ISSUE 3 satellite).  The configs are
tiny (3 labor states, 10-point grids, short horizons) — the code paths are
the production ones.
"""

import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from aiyagari_hark_tpu.utils.checkpoint import load_ks_checkpoint
from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig
from aiyagari_hark_tpu.utils.resilience import (
    Interrupted,
    clear_interrupt,
    request_interrupt,
)

AGENT = AgentConfig(labor_states=3, a_count=10, agent_count=40)
ECON = EconomyConfig(labor_states=3, act_T=150, t_discard=30,
                     verbose=False, tolerance=0.02, max_loops=3)
KWARGS = dict(seed=0, sim_method="distribution", dist_count=32)


def _bump_sidecar_tag(sidecar: str, delta: int = 7) -> None:
    """Rewrite the sidecar's iteration tag in place — the on-disk state a
    kill BETWEEN the sidecar write and the checkpoint write leaves behind
    (the sidecar is written first, so its tag runs ahead)."""
    with np.load(sidecar) as data:
        arrays = {k: data[k] for k in data.files}
    # leaf_000000 is the tag: the sidecar tree is (tag, state...) and
    # save_pytree flattens depth-first
    arrays["leaf_000000"] = arrays["leaf_000000"] + delta
    np.savez(sidecar, **arrays)


def test_ks_torn_checkpoint_pair_resumes_loudly(tmp_path):
    """A torn (old checkpoint, newer sidecar) pair must resume with the
    documented LOUD approximate degradation — fresh initial distribution,
    tag-mismatch warning — and still complete; and a checkpoint missing
    its sidecar entirely must warn the same way."""
    ck = str(tmp_path / "ks.npz")
    sidecar = ck + ".dist.npz"
    part = solve_ks_economy(AGENT, ECON.replace(max_loops=2), **KWARGS,
                            checkpoint_path=ck)
    assert len(part.records) == 2
    tag0 = int(load_ks_checkpoint(ck).iteration)

    _bump_sidecar_tag(sidecar)
    with pytest.warns(UserWarning,
                      match="interrupted between the two writes"):
        torn = solve_ks_economy(AGENT, ECON, **KWARGS, checkpoint_path=ck)
    # the resume really continued from the checkpoint's iteration count
    assert all(r.iteration >= tag0 for r in torn.records)
    assert np.isfinite(np.asarray(torn.afunc.intercept)).all()

    # checkpoint copied without its sidecar: same loud degradation
    import os

    os.remove(sidecar)
    with pytest.warns(UserWarning, match="resuming from a fresh initial "
                                         "distribution"):
        solo = solve_ks_economy(AGENT, ECON, **KWARGS, checkpoint_path=ck)
    assert np.isfinite(np.asarray(solo.afunc.intercept)).all()


def test_matched_sidecar_resumes_exactly(tmp_path):
    """The healthy pair (tags match) must resume WITHOUT the approximate-
    resume warning: the carried distribution is restored, so the continued
    trajectory equals the uninterrupted one (the contract the torn pair
    degrades from)."""
    import warnings

    ck = str(tmp_path / "ks.npz")
    full = solve_ks_economy(AGENT, ECON, **KWARGS)
    solve_ks_economy(AGENT, ECON.replace(max_loops=2), **KWARGS,
                     checkpoint_path=ck)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        resumed = solve_ks_economy(AGENT, ECON, **KWARGS,
                                   checkpoint_path=ck)
    assert not [w for w in caught
                if "approximate" in str(w.message)], (
        "healthy checkpoint/sidecar pair must resume exactly, not "
        "degrade to the approximate path")
    np.testing.assert_allclose(np.asarray(resumed.afunc.intercept),
                               np.asarray(full.afunc.intercept),
                               atol=1e-10)


def test_ks_preemption_flushes_checkpoint_and_resumes(tmp_path):
    """A shutdown requested mid-run is honored at the next outer-iteration
    boundary: the checkpoint for the completed iteration is on disk, the
    typed Interrupted carries the resume path, and a rerun continues the
    trajectory to the uninterrupted result."""
    ck = str(tmp_path / "ks.npz")
    full = solve_ks_economy(AGENT, ECON, **KWARGS)
    try:
        request_interrupt()
        with pytest.raises(Interrupted) as ei:
            solve_ks_economy(AGENT, ECON, **KWARGS, checkpoint_path=ck)
    finally:
        clear_interrupt()
    assert ei.value.resume_path == ck
    assert ei.value.progress["iteration"] == 1   # stopped after iter 1
    assert int(load_ks_checkpoint(ck).iteration) == 1

    resumed = solve_ks_economy(AGENT, ECON, **KWARGS, checkpoint_path=ck)
    assert [r.iteration for r in resumed.records] == list(
        range(1, 1 + len(resumed.records)))
    np.testing.assert_allclose(np.asarray(resumed.afunc.intercept),
                               np.asarray(full.afunc.intercept),
                               atol=1e-10)
