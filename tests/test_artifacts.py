"""Committed-artifact coherence: the repo-root evidence files must tell
the same story the docs and module docstrings claim (VERDICT r4 weak-item
4's closing condition — "no committed artifact contradicts the module's
own accuracy standard without comment" — made machine-checked instead of
editorial).

Pure-JSON tests (no jax), so they run in the fast profile and keep
guarding the artifacts even when the heavyweight solves are skipped.
"""

import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    path = os.path.join(REPO, name)
    if not os.path.exists(path):
        pytest.skip(f"{name} not committed in this checkout")
    with open(path) as f:
        return json.load(f)


def test_results_den_haan_side_by_side():
    """The den Haan block must carry BOTH engines, and each must meet the
    bound its docs claim: the pinned engine the 'fraction of a percent'
    accuracy standard (models/diagnostics.py module docstring), the MC
    panel rule the 'moderate' bound that its EIV-attenuated slope
    predicts (percent-level, under the 5%/10% regression guards of
    tests/test_diagnostics.py)."""
    res = _load("results.json")
    assert "den_haan_max_error_pct" in res
    assert "den_haan_pinned_max_error_pct" in res, (
        "results.json lost the pinned-engine side-by-side (VERDICT r4 "
        "weak-item 4); regenerate with `python reproduce.py`")
    assert res["den_haan_pinned_converged"] is True
    assert 0.0 < res["den_haan_pinned_max_error_pct"] < 1.0
    assert 0.0 < res["den_haan_pinned_mean_error_pct"] < 0.5
    assert 0.0 < res["den_haan_mean_error_pct"] < 5.0
    assert res["den_haan_max_error_pct"] < 10.0
    # the pinned engine must actually be the better forecaster — that is
    # the point of reporting it next to the panel rule
    assert (res["den_haan_pinned_max_error_pct"]
            < res["den_haan_max_error_pct"])


def test_results_equilibrium_sanity():
    """The committed equilibrium sits where every engine and the
    reference put it, and the solve converged."""
    res = _load("results.json")
    assert res["converged"] is True
    assert 3.5 < res["equilibrium_return_pct"] < 4.5
    assert 20.0 < res["equilibrium_saving_rate_pct"] < 27.0
    # the EIV-attenuation story quoted in diagnostics.py/DESIGN §3 as an
    # ORDERING, not a band: the MC-fit slope sits strictly between the
    # constant truth (0) and the ~1.2 deterministic transition slope.
    # Pinning a tighter band (the old 1.0 < slope < 1.2) made the suite
    # fail on any legitimate reseed of results.json whose draw attenuates
    # harder (ADVICE r5 #3) — the attenuation direction is the claim, the
    # exact magnitude is seed-dependent.
    for slope in res["afunc_slope"]:
        assert 0.0 < slope < 1.2
    ref = res["reference_goldens"]
    assert ref["r_pct"] == 4.178 and ref["solve_minutes"] == 27.12


def test_tpu_record_core_claims():
    """The durable TPU record's headline fields: a real accelerator
    capture (backend tpu), a four-digit speedup over the reference-
    equivalent work, and compiled-Mosaic correctness within the 1 bp
    budget.  Only stable fields are pinned — the record is overwritten
    phase-by-phase on every accelerator bench run."""
    rec = _load("bench_tpu_last.json")
    assert rec["backend"] in ("tpu", "axon")
    assert rec["metric"] == "table2_sweep_wall_s"
    assert 0.0 < rec["value"] < 60.0
    assert rec["vs_baseline"] > 1000.0
    assert rec["captured_at"]
    if rec.get("pallas_vs_dense_max_bp") is not None:
        assert rec["pallas_vs_dense_max_bp"] <= 1.0
    if rec.get("r_star_f32_f64_max_bp") is not None:
        assert rec["r_star_f32_f64_max_bp"] <= 1.0
