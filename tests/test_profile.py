"""Performance observability (ISSUE 10, DESIGN §10b): cost ledger,
roofline taxonomy, per-device telemetry, flight recorder.

Four contracts:

* **Measured cost attribution** — ``CostLedger.capture`` on CPU records
  XLA's own ``cost_analysis()`` (flops/bytes present,
  ``cost_source="xla_cost_analysis"``) plus real lowering/compile
  walls; a backend that cannot serve cost analysis records a REASON,
  never a crash, and launch aggregation keeps working.
* **Roofline classification** — the latency/memory/compute table is
  deterministic and pinned input-by-input.
* **Bit-identity** — a profiled sweep (``ObsConfig(profile=True)``)
  produces byte-identical rows/statuses to a plain sweep: capture is an
  AOT side channel, never a solver-path change.
* **Flight recorder** — a quarantine-ladder exhaustion dumps the ring
  atomically (valid JSON, recent events embedded, metrics snapshot
  attached) and journals exactly one FLIGHT_RECORD_DUMP.

Sweep configs mirror ``tests/test_obs.py`` so this module rides the same
warm jit caches instead of compiling its own programs.
"""

import json
import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.obs import (
    ObsConfig,
    build_obs,
    read_journal,
)
from aiyagari_hark_tpu.obs.profile import (
    ROOFLINE_COMPUTE,
    ROOFLINE_LATENCY,
    ROOFLINE_MEMORY,
    ROOFLINE_UNKNOWN,
    CostLedger,
    DeviceTelemetry,
    classify_roofline,
    peak_membw_per_chip,
)
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.timing import (
    Stopwatch,
    flop_report,
    record_flop_fields,
    stopwatch,
)

# Same cache keys as tests/test_obs.py (its sweep drills).
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
SMALL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                    schedule="balanced", n_buckets=2)
LOCKSTEP = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
DRILL_KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
                max_bisect=24)


# ---------------------------------------------------------------------------
# Cost ledger: capture with cost_analysis present.
# ---------------------------------------------------------------------------

def test_cost_ledger_captures_xla_cost_analysis_on_cpu():
    ledger = CostLedger(backend="cpu")
    fn = jax.jit(lambda x: jnp.matmul(
        x, x, preferred_element_type=jnp.float64))
    x = jnp.ones((32, 32), dtype=jnp.float64)
    key = ("test", "matmul", 32)
    entry = ledger.capture(key, fn, (x,), label="test/matmul32")
    assert entry.cost_source == "xla_cost_analysis"
    assert entry.flops is not None and entry.flops > 0
    assert entry.bytes_accessed is not None and entry.bytes_accessed > 0
    assert entry.lowering_s is not None and entry.lowering_s >= 0
    assert entry.compile_s is not None and entry.compile_s > 0
    # memoized: a second capture is the same entry, not a recompile
    assert ledger.capture(key, fn, (x,)) is entry

    ledger.record_launch(key, 0.25)
    ledger.record_launch(key, 0.25)
    assert entry.launches == 2
    assert entry.launch_wall_s == pytest.approx(0.5)
    assert entry.achieved_flops_per_sec() == pytest.approx(
        entry.flops * 2 / 0.5)
    assert entry.arithmetic_intensity() == pytest.approx(
        entry.flops / entry.bytes_accessed)

    snap = ledger.snapshot()
    json.dumps(snap)            # JSON-able by construction
    assert snap["executables"] == 1
    assert snap["launches"] == 2
    assert snap["measured_flops_total"] == pytest.approx(entry.flops * 2)
    assert snap["cost_sources"] == {"xla_cost_analysis": 1}
    assert snap["roofline"] in (ROOFLINE_MEMORY, ROOFLINE_COMPUTE,
                                ROOFLINE_LATENCY)


def test_cost_ledger_records_reason_when_cost_analysis_absent():
    ledger = CostLedger(backend="cpu")

    class NoAOT:
        def lower(self, *a):
            raise NotImplementedError("no AOT path on this backend")

    entry = ledger.capture(("k",), NoAOT(), (), label="broken")
    assert entry.cost_source.startswith("unavailable: NotImplementedError")
    assert entry.flops is None and entry.bytes_accessed is None
    # launch aggregation still works; derived fields stay honest Nones
    ledger.record_launch(("k",), 1.0)
    assert entry.launches == 1
    assert entry.achieved_flops_per_sec() is None
    snap = ledger.snapshot()
    assert snap["measured_flops_total"] is None
    assert snap["roofline"] == ROOFLINE_UNKNOWN
    assert snap["cost_sources"] == {"unavailable": 1}
    assert ledger.flops_model_vs_measured_ratio(1e9) is None


def test_snapshot_roofline_not_inflated_by_launch_count():
    """The run-level roofline must classify per-launch work: totals
    already carry the launch multiplier, and re-multiplying inside the
    classifier would promote a latency-bound run to memory/compute
    once it launches often enough (the double-count regression)."""
    ledger = CostLedger(peak_flops=V5E_FLOPS, peak_bytes_per_s=V5E_BW)
    key = ("k",)
    entry = ledger.capture(key, object(), ())     # capture fails ->
    entry.flops = 1e9                             # synthesize the cost
    entry.bytes_accessed = 1e7                    # analysis fields
    entry.cost_source = "xla_cost_analysis"
    for _ in range(100):
        ledger.record_launch(key, 0.01)           # total wall 1.0 s
    # honest achieved = 1e9 * 100 / 1.0 = 1e11; ceiling = AI(100) * bw
    # ~ 8.2e13 -> util ~ 1.2e-3 << 2% -> latency.  A double count
    # (x100 again) would read 12% and misclassify as compute/memory.
    assert entry.roofline(V5E_FLOPS, V5E_BW) == ROOFLINE_LATENCY
    snap = ledger.snapshot()
    assert snap["roofline"] == ROOFLINE_LATENCY
    assert snap["achieved_flops_per_sec"] == pytest.approx(1e11)


def test_snapshot_slug_collision_keeps_every_entry():
    """Two ledger keys sharing a display label (same executable with
    and without a fault hook) must stay two snapshot entries — the
    executable-ladder audit cannot silently merge them."""
    ledger = CostLedger(backend="cpu")
    fn = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((4,), dtype=jnp.float64)
    ledger.capture(("a", None), fn, (x,), label="sweep/cold4")
    ledger.capture(("a", "nan"), fn, (x,), label="sweep/cold4")
    ledger.record_launch(("a", None), 0.1)
    ledger.record_launch(("a", "nan"), 0.2)
    snap = ledger.snapshot()
    assert snap["executables"] == 2
    assert len(snap["entries"]) == 2
    assert set(snap["entries"]) == {"sweep_cold4", "sweep_cold4_2"}
    walls = sorted(e["launch_wall_s"] for e in snap["entries"].values())
    assert walls == [pytest.approx(0.1), pytest.approx(0.2)]


def test_cost_ledger_publish_mirrors_registry():
    from aiyagari_hark_tpu.obs import MetricsRegistry

    ledger = CostLedger(backend="cpu")
    fn = jax.jit(lambda x: x + 1.0)
    x = jnp.ones((8,), dtype=jnp.float64)
    ledger.capture(("k",), fn, (x,), label="test/add")
    ledger.record_launch(("k",), 0.1)
    reg = MetricsRegistry()
    ledger.publish(reg)
    names = reg.names()
    assert "aiyagari_profile_executables" in names
    assert "aiyagari_profile_launch_wall_s_test_add" in names
    assert reg.gauge("aiyagari_profile_launches").value == 1.0


# ---------------------------------------------------------------------------
# Roofline classification table.
# ---------------------------------------------------------------------------

V5E_FLOPS, V5E_BW = 197e12, 819e9       # ridge ~240 FLOP/byte


@pytest.mark.parametrize("flops,bytes_,wall,launches,pf,pbw,expect", [
    # no cost analysis / no launches -> unknown
    (None, 1e6, 1.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_UNKNOWN),
    (1e9, None, 1.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_UNKNOWN),
    (1e9, 1e6, 1.0, 0, V5E_FLOPS, V5E_BW, ROOFLINE_UNKNOWN),
    (1e9, 1e6, 0.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_UNKNOWN),
    # the measured sweep regime: tiny program, achieved ~1e11 << ceiling
    # -> latency-bound on the accelerator
    (1e8, 1e6, 1.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_LATENCY),
    # high-AI program achieving ~60% of peak -> compute-bound
    (1.2e14, 1e9, 1.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_COMPUTE),
    # low-AI program saturating ~60% of its bandwidth roof -> memory
    (5e11, 1e12, 1.0, 1, V5E_FLOPS, V5E_BW, ROOFLINE_MEMORY),
    # no published peak (CPU): sub-ms per-launch wall -> latency
    (1e6, 1e6, 5e-4, 1, None, None, ROOFLINE_LATENCY),
    # no published peak: AI 1000 >= default ridge -> compute
    (1e9, 1e6, 1.0, 1, None, None, ROOFLINE_COMPUTE),
    # no published peak: AI 0.2 < default ridge -> memory
    (2e5, 1e6, 1.0, 1, None, None, ROOFLINE_MEMORY),
])
def test_roofline_classification_table(flops, bytes_, wall, launches,
                                       pf, pbw, expect):
    assert classify_roofline(flops, bytes_, wall, launches,
                             peak_flops=pf,
                             peak_bytes_per_s=pbw) == expect


def test_peak_membw_graceful_off_accelerator():
    assert peak_membw_per_chip("cpu") == (None, False)


# ---------------------------------------------------------------------------
# Profiled sweep: bit-identity + snapshot/journal plumbing.
# ---------------------------------------------------------------------------

def test_profiled_sweep_bit_identical_and_snapshotted(tmp_path):
    jp = str(tmp_path / "events.jsonl")
    obs = build_obs(ObsConfig(enabled=True, profile=True,
                              journal_path=jp,
                              trace_path=str(tmp_path / "trace.json")))
    res_on = run_table2_sweep(SMALL, dtype=jnp.float64, obs=obs, **KW)
    res_off = run_table2_sweep(SMALL, dtype=jnp.float64, **KW)
    # the AOT capture is a side channel: bits must not move
    assert np.array_equal(res_on.r_star_pct, res_off.r_star_pct)
    assert np.array_equal(res_on.saving_rate_pct, res_off.saving_rate_pct)
    assert np.array_equal(res_on.status, res_off.status)

    snap = obs.cost_ledger.snapshot()
    assert snap["executables"] >= 1
    assert snap["launches"] >= 2            # two buckets minimum
    assert snap["launch_wall_s"] > 0.0
    assert snap["cost_sources"].get("xla_cost_analysis", 0) >= 1
    assert snap["measured_flops_total"] > 0
    ratio = obs.cost_ledger.flops_model_vs_measured_ratio(1e12)
    assert ratio is not None and ratio > 0

    obs.close()
    # exactly one PROFILE_SNAPSHOT journal line, under this run_id
    snaps = read_journal(jp, run_id=obs.run_id, event="PROFILE_SNAPSHOT")
    assert len(snaps) == 1
    assert snaps[0]["executables"] == snap["executables"]
    # the trace carries counter-track samples for the launches
    with open(str(tmp_path / "trace.json")) as f:
        trace = json.load(f)
    counters = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) >= snap["launches"]
    # registry mirror landed at close
    assert "aiyagari_profile_executables" in obs.registry.names()
    # lane telemetry gauges landed at the bucket seams
    assert "aiyagari_sweep_bucket_lane_occupancy" in obs.registry.names()


@pytest.mark.slow
def test_profiled_sweep_bit_identical_to_committed_goldens():
    """Profiling on, the COMMITTED golden cells must come back
    bit-for-bit (the --profile-smoke acceptance, runnable in-tree; the
    fast profile pins on/off bit-identity on the small config above)."""
    golden_path = os.path.join(os.path.dirname(__file__), "data",
                               "table2_golden_test.json")
    golden = json.load(open(golden_path))
    obs = build_obs(ObsConfig(enabled=True, profile=True))
    res = run_table2_sweep(SweepConfig(), dtype=jnp.float64, obs=obs,
                           **golden["config"])
    obs.close()
    assert np.array_equal(
        np.asarray(res.r_star_pct),
        np.asarray(golden["r_star_pct"], dtype=np.float64))


# ---------------------------------------------------------------------------
# Device telemetry: graceful off-TPU.
# ---------------------------------------------------------------------------

def test_device_telemetry_graceful_on_cpu(tmp_path):
    jp = str(tmp_path / "events.jsonl")
    obs = build_obs(ObsConfig(enabled=True, profile=True,
                              journal_path=jp))
    n = obs.sample_devices(where="test")
    # CPU devices expose no memory_stats: zero devices report, nothing
    # raises, the sample is still counted
    assert n == 0
    assert obs.telemetry.samples == 1
    assert obs.telemetry.devices_without_stats == len(jax.devices())
    assert read_journal(jp, event="DEVICE_MEM_HIGH_WATER") == []
    obs.close()


def test_device_telemetry_high_water_events_monotone(tmp_path):
    """With synthetic stats, DEVICE_MEM_HIGH_WATER fires only on a NEW
    per-device peak — one event per growth, none on flat samples."""
    jp = str(tmp_path / "events.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    tel = DeviceTelemetry()

    class FakeDev:
        def __init__(self):
            self.stats = {"bytes_in_use": 100, "peak_bytes_in_use": 100,
                          "bytes_limit": 1000}

        def memory_stats(self):
            return self.stats

    dev = FakeDev()
    import unittest.mock as mock
    with mock.patch.object(jax, "devices", lambda *a: [dev]):
        assert tel.sample(obs, where="a") == 1     # first peak: event
        assert tel.sample(obs, where="b") == 1     # flat: no event
        dev.stats = dict(dev.stats, bytes_in_use=500,
                         peak_bytes_in_use=500)
        tel.sample(obs, where="c")                 # growth: event
    events = read_journal(jp, event="DEVICE_MEM_HIGH_WATER")
    assert [e["where"] for e in events] == ["a", "c"]
    assert events[-1]["bytes"] == 500
    assert tel.high_water() == {0: 500.0}
    obs.close()


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------

def test_flight_recorder_dumps_on_quarantine_exhaustion(tmp_path):
    jp = str(tmp_path / "events.jsonl")
    fp = str(tmp_path / "flight.json")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = run_table2_sweep(
            LOCKSTEP, dtype=jnp.float64,
            obs=ObsConfig(enabled=True, journal_path=jp, flight_path=fp),
            inject_fault={"cell": 1, "at_iter": 1, "mode": "nan"},
            max_retries=0, **DRILL_KW)
    assert list(res.failed_cells()) == [1]
    assert os.path.exists(fp)
    dump = json.load(open(fp))
    assert dump["reason"].startswith("aiyagari sweep: 1 cell(s)")
    assert dump["attrs"]["cells"] == [1]
    kinds = {e["kind"] for e in dump["entries"]}
    assert "event" in kinds                 # recent journal events ride
    assert any(e.get("event") == "BUCKET_LAUNCH"
               for e in dump["entries"])
    assert dump["metrics"] is not None      # registry snapshot embedded
    assert dump["entries_dropped"] == 0
    # exactly one typed journal line, pointing at the artifact
    dumps = read_journal(jp, event="FLIGHT_RECORD_DUMP")
    assert len(dumps) == 1 and dumps[0]["path"] == fp


def test_flight_recorder_ring_is_bounded(tmp_path):
    obs = build_obs(ObsConfig(enabled=True, flight_limit=4,
                              journal_path=str(tmp_path / "j.jsonl"),
                              flight_path=str(tmp_path / "f.json")))
    for i in range(10):
        obs.event("RUN_START", i=i)         # any typed event will do
    assert len(obs.flight.entries()) == 4
    assert obs.flight.dropped == 7          # RUN_START at build + 10 - 4
    path = obs.dump_flight("test")
    dump = json.load(open(path))
    assert len(dump["entries"]) <= 4 + 1    # ring (+ the dump's event)
    assert dump["entries_dropped"] >= 7
    obs.close()


def test_no_dump_without_quarantine_exhaustion(tmp_path):
    jp = str(tmp_path / "events.jsonl")
    fp = str(tmp_path / "flight.json")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        res = run_table2_sweep(
            LOCKSTEP, dtype=jnp.float64,
            obs=ObsConfig(enabled=True, journal_path=jp, flight_path=fp),
            inject_fault={"cell": 1, "at_iter": 1, "mode": "nan"},
            max_retries=2, **DRILL_KW)
    # the ladder recovered the cell: no crash artifact, no dump event
    assert len(res.failed_cells()) == 0
    assert not os.path.exists(fp)
    assert read_journal(jp, event="FLIGHT_RECORD_DUMP") == []


# ---------------------------------------------------------------------------
# flop_report provenance (ISSUE 10 satellite) + stopwatch.
# ---------------------------------------------------------------------------

def test_flop_report_provenance_analytic_vs_measured():
    analytic = flop_report(100, 1000, 2.0, 32, 7, 500, dense_dist=False,
                           backend="cpu")
    assert analytic["flops_provenance"] == "analytic"
    assert analytic["flops_per_sec"] > 0
    measured = flop_report(100, 1000, 2.0, 32, 7, 500, dense_dist=False,
                           backend="cpu", measured_flops=4.0e9)
    assert measured["flops_provenance"] == "xla_cost_analysis"
    assert measured["flops_per_sec"] == round(4.0e9 / 2.0)
    # degenerate wall: nulls, provenance null too (nothing was measured)
    nulls = flop_report(1, 1, None, 32, 7, 500, False, "cpu")
    assert nulls == {"flops_per_sec": None, "mfu_pct": None,
                     "peak_flops_assumed": False,
                     "flops_provenance": None}


def test_record_flop_fields_stamps_prefix():
    rec = {}
    out = record_flop_fields(rec, "phase_", 100, 1000, 2.0, 32, 7, 500,
                             dense_dist=False, backend="cpu",
                             measured_flops=2.0e9)
    assert out is rec
    assert rec["phase_flops_per_sec"] == round(1.0e9)
    assert rec["phase_flops_provenance"] == "xla_cost_analysis"
    assert rec["phase_peak_flops_assumed"] is False
    assert rec["phase_mfu_pct"] is None     # no CPU peak


def test_stopwatch_fills_on_exit_and_elapsed_runs():
    with stopwatch() as sw:
        inner = sw.elapsed()
        assert inner >= 0.0
    assert np.isfinite(sw.seconds) and sw.seconds >= inner
    direct = Stopwatch()
    assert direct.elapsed() >= 0.0
    assert np.isnan(direct.seconds)
