"""Epstein-Zin recursive preferences (models/epstein_zin.py).

Oracles: the exact CRRA reduction at gamma = rho (policy knots AND the
general-equilibrium rate must reproduce the CRRA solver), fixed-point
self-consistency of the converged (c, V) pair, value monotonicity in
risk aversion, and the precautionary comparative static gamma alone
drives."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.epstein_zin import (
    as_household_policy,
    egm_step_ez,
    solve_ez_equilibrium,
    solve_ez_household,
)
from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    solve_household,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')

ALPHA, DELTA, BETA = 0.36, 0.08, 0.96
R, W = 1.03, 1.2


@pytest.fixture(scope="module")
def model():
    return build_simple_model(labor_states=3, a_count=30, dist_count=120)


def test_crra_reduction_policy(model):
    """gamma = rho = 2 must reproduce the CRRA household exactly (the
    risk-adjustment weights collapse to one)."""
    ez, _, _, _ = solve_ez_household(R, W, model, BETA, 2.0, 2.0, tol=1e-9)
    crra, _, _, _ = solve_household(R, W, model, BETA, 2.0, tol=1e-9)
    np.testing.assert_allclose(np.asarray(ez.c_knots),
                               np.asarray(crra.c_knots), atol=1e-6)
    np.testing.assert_allclose(np.asarray(ez.m_knots),
                               np.asarray(crra.m_knots), atol=1e-6)


def test_converged_policy_is_fixed_point(model):
    """The Euler and aggregator equations in one check: a further EZ-EGM
    step from the converged (c, V) pair must not move it."""
    ez, _, diff, _ = solve_ez_household(R, W, model, BETA, 2.0, 8.0,
                                     tol=1e-10)
    stepped = egm_step_ez(ez, R, W, model, BETA, 2.0, 8.0)
    assert float(jnp.max(jnp.abs(stepped.c_knots - ez.c_knots))) < 1e-9
    assert float(jnp.max(jnp.abs(stepped.v_knots - ez.v_knots))) < 1e-8


def test_value_falls_with_risk_aversion(model):
    """Same EIS, more risk aversion: lifetime value (in consumption
    units) falls at interior wealth — risk is priced harder.  (Near the
    borrowing constraint the comparison is between two DIFFERENT optimal
    policies and the ordering is not a theorem, so the check starts
    above it.)"""
    lo, _, _, _ = solve_ez_household(R, W, model, BETA, 2.0, 2.0)
    hi, _, _, _ = solve_ez_household(R, W, model, BETA, 2.0, 8.0)
    from aiyagari_hark_tpu.ops.interp import interp1d_rowwise

    m = jnp.tile(jnp.linspace(4.0, 20.0, 10)[None, :], (3, 1))
    v_lo = interp1d_rowwise(m, lo.m_knots, lo.v_knots)
    v_hi = interp1d_rowwise(m, hi.m_knots, hi.v_knots)
    assert (np.asarray(v_hi) < np.asarray(v_lo)).all()


@pytest.fixture(scope="module")
def equilibria(model):
    eq_crra = solve_bisection_equilibrium(model, BETA, 2.0, ALPHA, DELTA)
    eq_ez = solve_ez_equilibrium(model, BETA, 2.0, 2.0, ALPHA, DELTA)
    eq_ra = solve_ez_equilibrium(model, BETA, 2.0, 8.0, ALPHA, DELTA)
    return eq_crra, eq_ez, eq_ra


def test_crra_reduction_equilibrium(equilibria):
    eq_crra, eq_ez, _ = equilibria
    np.testing.assert_allclose(float(eq_ez.r_star), float(eq_crra.r_star),
                               atol=2e-6)
    assert abs(float(eq_ez.excess)) < 1e-5 * float(eq_ez.capital)


def test_risk_aversion_alone_is_precautionary(equilibria):
    """Raising gamma at fixed rho (EIS unchanged) must lower r* — the
    separation CRRA cannot express."""
    _, eq_ez, eq_ra = equilibria
    assert float(eq_ra.r_star) < float(eq_ez.r_star) - 1e-3
    assert float(eq_ra.capital) > float(eq_ez.capital)


def test_aggregate_ez_welfare(model, equilibria):
    """Welfare in consumption units sits inside the consumption range,
    and a uniformly scaled-up value function scales welfare one-for-one
    (the homogeneity that makes EZ CE comparisons a plain ratio)."""
    from aiyagari_hark_tpu.models.epstein_zin import aggregate_ez_welfare

    _, eq_ez, _ = equilibria
    R_, W_ = 1.0 + float(eq_ez.r_star), float(eq_ez.wage)
    w0 = float(aggregate_ez_welfare(eq_ez.policy, eq_ez.distribution,
                                    R_, W_, model))
    # lifetime CE consumption sits near mean consumption under the
    # stationary distribution (a real bound, unlike the knot range whose
    # ends are the 1e-7 constraint eps and the top of the grid)
    m = R_ * np.asarray(model.dist_grid)[:, None] \
        + W_ * np.asarray(model.labor_levels)[None, :]
    from aiyagari_hark_tpu.models.household import consumption_at

    c_bar = float(np.sum(np.asarray(eq_ez.distribution)
                         * np.asarray(consumption_at(
                             as_household_policy(eq_ez.policy),
                             jnp.asarray(m.T))).T))
    assert 0.5 * c_bar < w0 < 2.0 * c_bar
    scaled = eq_ez.policy._replace(v_knots=1.1 * eq_ez.policy.v_knots)
    w1 = float(aggregate_ez_welfare(scaled, eq_ez.distribution, R_, W_,
                                    model))
    np.testing.assert_allclose(w1 / w0, 1.1, rtol=1e-10)


def test_ez_equilibrium_is_jittable(model):
    f = jax.jit(lambda g: solve_ez_equilibrium(
        model, BETA, 2.0, g, ALPHA, DELTA, max_bisect=20))
    res = f(jnp.asarray(4.0))
    assert np.isfinite(float(res.r_star))


def test_vmap_over_risk_aversion(model):
    """A gamma sweep is one batched XLA program (the same pattern as the
    Table II sweep), and r* is monotone decreasing in gamma across it."""
    gammas = jnp.asarray([2.0, 4.0, 8.0])
    r = jax.vmap(lambda g: solve_ez_equilibrium(
        model, BETA, 2.0, g, ALPHA, DELTA, max_bisect=25).r_star)(gammas)
    r = np.asarray(r)
    assert np.isfinite(r).all()
    assert (np.diff(r) < 0).all()
