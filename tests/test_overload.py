"""Overload-resilient serving (ISSUE 8): admission control, priority
load shedding, degraded answers, regional circuit breakers, and the
typed-event contract under saturation.

The load-bearing contracts:

* every over-capacity outcome is TYPED — ``Overloaded`` / ``LoadShed``
  / ``CircuitOpen`` / ``DeadlineExceeded`` / a tagged degraded result —
  and journaled exactly once (injected == journaled);
* exact store hits bypass the overload layer entirely (µs hits at 100%
  cold-miss saturation);
* with admission enabled but unsaturated, served bits are identical to
  the PR 4 packing-independence reference (``reference_solve``);
* no future is ever left unresolved (threaded soak, slow-marked).
"""

import threading

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import ObsConfig, read_journal
from aiyagari_hark_tpu.serve import (
    AdmissionPolicy,
    CircuitBreaker,
    CircuitOpen,
    DeadlineExceeded,
    EquilibriumService,
    EquilibriumSolveFailed,
    LoadShed,
    ManualClock,
    MicroBatcher,
    Overloaded,
    Priority,
    ServeQueueFull,
    make_query,
    predicted_work,
)
from aiyagari_hark_tpu.solver_health import (
    CIRCUIT_OPEN,
    LOAD_SHED,
    OVERLOADED,
    is_failure,
    status_name,
)

# The suite-shared tiny-cell configuration (tests/test_serve.py), so the
# compiled executables are reused across files.
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)


def manual_service(**over):
    kw = dict(start_worker=False, max_batch=4, max_wait_s=60.0,
              ladder=(1, 2, 4))
    kw.update(over)
    return EquilibriumService(**kw)


def assert_rows_equal(a, b):
    assert (a.r_star, a.capital, a.labor) == (b.r_star, b.capital, b.labor)
    assert (a.bisect_iters, a.egm_iters, a.dist_iters) == (
        b.bisect_iters, b.egm_iters, b.dist_iters)
    assert a.status == b.status


# ---------------------------------------------------------------------------
# Batcher satellites: injected-clock offer, payload, shedding, ready().
# ---------------------------------------------------------------------------

def test_queue_full_carries_retry_after_payload():
    clk = ManualClock()
    b = MicroBatcher(max_batch=4, max_queue=2, clock=clk)
    b.offer("g", 1)
    clk.advance(0.5)
    b.offer("g", 2)
    with pytest.raises(ServeQueueFull) as exc:
        b.offer("g", 3, block=False)
    assert exc.value.depth == 2
    assert exc.value.max_queue == 2
    assert exc.value.oldest_wait_s == pytest.approx(0.5)


def test_offer_block_timeout_rides_the_injected_clock():
    """A blocked offer's timeout is measured on the injected clock:
    advancing the fake clock past it and kicking wakes the caller with
    the typed, payload-carrying ``ServeQueueFull`` — deterministically,
    long before the real-time backstop."""
    clk = ManualClock()
    b = MicroBatcher(max_batch=4, max_queue=1, clock=clk)
    b.offer("g", 1)
    outcome = {}

    def blocked():
        try:
            b.offer("g", 2, timeout=30.0)
            outcome["raised"] = False
        except ServeQueueFull as e:
            outcome["raised"] = True
            outcome["depth"] = e.depth
    t = threading.Thread(target=blocked)
    t.start()
    # let the thread enter the wait, then expire the injected clock
    import time
    time.sleep(0.05)
    clk.advance(31.0)
    b.kick()
    t.join(5.0)
    assert not t.is_alive(), "offer must wake on the injected clock"
    assert outcome == {"raised": True, "depth": 1}


def test_offer_real_time_backstop_with_stalled_fake_clock():
    """A fake clock nobody advances must not block a caller forever:
    the real-time backstop of the same magnitude still fires."""
    b = MicroBatcher(max_batch=4, max_queue=1, clock=ManualClock())
    b.offer("g", 1)
    with pytest.raises(ServeQueueFull):
        b.offer("g", 2, timeout=0.02)


def test_shed_lowest_orders_by_class_then_youngest():
    clk = ManualClock()
    b = MicroBatcher(max_batch=8, clock=clk,
                     priority_of=lambda item: item[0])
    b.offer("g", (Priority.BATCH, "b0"))
    clk.advance(1.0)
    b.offer("g", (Priority.SPECULATIVE, "s0"))
    clk.advance(1.0)
    b.offer("g", (Priority.SPECULATIVE, "s1"))
    # lowest class first; youngest within the class
    assert b.shed_lowest()[1] == (Priority.SPECULATIVE, "s1")
    assert b.shed_lowest()[1] == (Priority.SPECULATIVE, "s0")
    # strictly-lower-class only: nothing below BATCH remains for a
    # BATCH-class displacement
    assert b.shed_lowest(max_class=Priority.BATCH) is None
    assert b.shed_lowest(max_class=Priority.INTERACTIVE)[1] == (
        Priority.BATCH, "b0")
    assert b.depth() == 0


def test_ready_matches_pop_ready_at_the_deadline_boundary():
    """ready()/pop_ready() must agree with next_deadline()'s arithmetic
    at the exact boundary instant (the load harness advances the clock
    to precisely that float)."""
    clk = ManualClock(t=0.0133457)
    b = MicroBatcher(max_batch=4, max_wait_s=0.005, clock=clk)
    b.offer("g", "r")
    nd = b.next_deadline()
    clk.t = nd
    assert b.ready()
    assert b.pop_ready() == [("g", ["r"])]


# ---------------------------------------------------------------------------
# Admission control.
# ---------------------------------------------------------------------------

def test_overloaded_reject_carries_depth_and_retry_after():
    pol = AdmissionPolicy(max_work=1.0, shed=False, est_batch_s=0.5)
    svc = manual_service(admission=pol)
    fut = svc.submit(make_query(3.0, 0.6, **KW))
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(1.0, 0.0, **KW))
    e = exc.value
    assert e.reason == "class_budget"
    assert e.depth == 1 and e.max_queue == svc.batcher.max_queue
    assert e.est_wait_s == e.retry_after_s == pytest.approx(0.5)
    assert e.status == OVERLOADED and is_failure(e.status)
    assert status_name(e.status) == "OVERLOADED"
    # draining frees the occupancy: the same query is admitted now
    svc.flush()
    assert not is_failure(fut.result(0).status)
    fut2 = svc.submit(make_query(1.0, 0.0, **KW))
    svc.flush()
    assert not is_failure(fut2.result(0).status)
    snap = svc.metrics.snapshot()
    assert snap["serve_overloaded"] == 1
    svc.close()


def test_occupancy_is_weighted_by_predicted_work():
    """Queue slots are weighted by the PR 2 work heuristic: a budget
    that admits two cheap high-ρ cells rejects the second slow-mixing
    ρ=0 cell."""
    w_cheap = predicted_work((3.0, 0.9, 0.2))
    w_slow = predicted_work((3.0, 0.0, 0.2))
    assert w_slow > w_cheap
    pol = AdmissionPolicy(max_work=2.05 * w_cheap, shed=False)
    svc = manual_service(admission=pol)
    svc.submit(make_query(3.0, 0.9, **KW))
    svc.submit(make_query(5.0, 0.9, **KW))      # ~same weight: admitted
    svc2 = manual_service(admission=pol)
    svc2.submit(make_query(3.0, 0.0, **KW))
    with pytest.raises(Overloaded):
        svc2.submit(make_query(5.0, 0.0, **KW))  # 2 x slow > budget
    svc.close()
    svc2.close()


def test_deadline_aware_admission_rejects_unmeetable_at_submit():
    pol = AdmissionPolicy(max_work=64.0, est_batch_s=1.0)
    svc = manual_service(admission=pol)
    svc.submit(make_query(3.0, 0.6, **KW))      # depth 1 -> est wait 1s
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(1.0, 0.0, **KW), deadline=0.5)
    assert exc.value.reason == "deadline_unmeetable"
    # a meetable deadline is admitted
    fut = svc.submit(make_query(1.0, 0.0, **KW), deadline=5.0)
    svc.flush()
    assert not is_failure(fut.result(0).status)
    svc.close()


def test_already_expired_deadline_rejected_at_submit():
    """ISSUE 8 satellite: a query whose deadline has effectively passed
    never occupies a queue slot — typed ``DeadlineExceeded`` at submit,
    counted APART from seam expirations (no admission policy needed)."""
    clk = ManualClock()
    svc = manual_service(clock=clk, max_wait_s=0.8)
    with pytest.raises(DeadlineExceeded):
        svc.submit(make_query(3.0, 0.6, **KW), deadline=0.0)
    assert svc.batcher.depth() == 0
    # a seam expiration still counts in the OTHER bucket
    fut = svc.submit(make_query(3.0, 0.6, **KW), deadline=0.5)
    clk.advance(1.0)                    # past max_wait: the batch pops,
    svc.pump()                          # the seam gate expires it
    with pytest.raises(DeadlineExceeded):
        fut.result(0)
    snap = svc.metrics.snapshot()
    assert snap["serve_deadline_rejects_submit"] == 1
    assert snap["serve_deadline_expirations"] == 1
    svc.close()


def test_exact_hits_bypass_admission_at_saturation():
    """The hit path must stay a dict lookup even at 100% occupancy."""
    pol = AdmissionPolicy(max_work=1.0, shed=False)
    svc = manual_service(admission=pol)
    hot = svc.query(3.0, 0.6, **KW)             # warm the store
    svc.submit(make_query(1.0, 0.0, **KW))      # saturate the budget
    with pytest.raises(Overloaded):
        svc.submit(make_query(5.0, 0.9, **KW))
    fut = svc.submit(make_query(3.0, 0.6, **KW))
    assert fut.done()                            # resolved AT submit
    assert fut.result().path == "hit"
    assert_rows_equal(fut.result(), hot)
    svc.close()


# ---------------------------------------------------------------------------
# Priority load shedding.
# ---------------------------------------------------------------------------

def test_interactive_displaces_youngest_speculative():
    clk = ManualClock()
    pol = AdmissionPolicy(max_work=2.0, class_shares=(1.0, 1.0, 1.0),
                          shed=True)
    svc = manual_service(admission=pol, clock=clk)
    fs0 = svc.submit(make_query(3.0, 0.9,
                                priority=Priority.SPECULATIVE, **KW))
    clk.advance(1.0)
    fs1 = svc.submit(make_query(5.0, 0.9,
                                priority=Priority.SPECULATIVE, **KW))
    clk.advance(1.0)
    qi = make_query(3.0, 0.0, priority=Priority.INTERACTIVE, **KW)
    fi = svc.submit(qi)
    # the YOUNGEST speculative was shed with the typed LoadShed payload
    with pytest.raises(LoadShed) as exc:
        fs1.result(0)
    e = exc.value
    assert e.priority == Priority.SPECULATIVE
    assert e.waited_s == pytest.approx(1.0)
    assert e.displaced_by == qi.key()
    assert e.status == LOAD_SHED
    assert not fs0.done()
    svc.flush()
    assert not is_failure(fi.result(0).status)
    assert not is_failure(fs0.result(0).status)
    assert svc.metrics.snapshot()["serve_load_sheds"] == 1
    svc.close()


def test_shedding_never_displaces_equal_or_higher_class():
    pol = AdmissionPolicy(max_work=1.0, class_shares=(1.0, 1.0, 1.0),
                          shed=True)
    svc = manual_service(admission=pol)
    fb = svc.submit(make_query(3.0, 0.6, priority=Priority.BATCH, **KW))
    with pytest.raises(Overloaded):
        svc.submit(make_query(1.0, 0.0, priority=Priority.BATCH, **KW))
    with pytest.raises(Overloaded):
        svc.submit(make_query(1.0, 0.0,
                              priority=Priority.SPECULATIVE, **KW))
    assert not fb.done()
    svc.flush()
    assert not is_failure(fb.result(0).status)
    svc.close()


def test_nested_class_budgets_reserve_interactive_headroom():
    """SPECULATIVE is capped at its share even when the queue is
    otherwise empty; the reserved headroom still admits INTERACTIVE."""
    w = predicted_work((3.0, 0.9, 0.2))
    pol = AdmissionPolicy(max_work=4.0 * w,
                          class_shares=(1.0, 0.5, 0.25), shed=False)
    svc = manual_service(admission=pol)
    svc.submit(make_query(3.0, 0.9, priority=Priority.SPECULATIVE, **KW))
    with pytest.raises(Overloaded):
        # a second speculative would exceed the 25% share
        svc.submit(make_query(5.0, 0.9,
                              priority=Priority.SPECULATIVE, **KW))
    svc.submit(make_query(5.0, 0.9, priority=Priority.INTERACTIVE, **KW))
    svc.close(drain=True)


def _occ_total(svc):
    with svc._occ_lock:
        return sum(svc._occupancy.values())


def test_futile_shed_kills_no_victims():
    """A victim must never be displaced for an arrival that gets
    rejected anyway: when even a FULL shed of every lower class could
    not admit the arrival, nothing is shed."""
    w_spec = predicted_work((3.0, 0.9, 0.2))
    w_int = predicted_work((3.0, 0.0, 0.2))
    w_arr = predicted_work((5.0, 0.0, 0.2))
    pol = AdmissionPolicy(max_work=(w_spec + w_int) * 1.001,
                          class_shares=(1.0, 1.0, 1.0), shed=True)
    # premise: with the speculative gone, INTERACTIVE + arrival still
    # exceeds the budget — shedding cannot possibly help
    assert w_int + w_arr > pol.max_work
    svc = manual_service(admission=pol)
    fs = svc.submit(make_query(3.0, 0.9,
                               priority=Priority.SPECULATIVE, **KW))
    fi = svc.submit(make_query(3.0, 0.0,
                               priority=Priority.INTERACTIVE, **KW))
    with pytest.raises(Overloaded):
        svc.submit(make_query(5.0, 0.0,
                              priority=Priority.INTERACTIVE, **KW))
    assert not fs.done(), "victim shed for a doomed arrival"
    assert svc.metrics.snapshot()["serve_load_sheds"] == 0
    svc.flush()
    assert not is_failure(fs.result(0).status)
    assert not is_failure(fi.result(0).status)
    svc.close()


def test_queue_full_rejection_releases_occupancy():
    """The queue_full rejection path must return its acquired weight:
    leaked occupancy would ratchet until admission rejects everything
    on an idle queue."""
    pol = AdmissionPolicy(max_work=1000.0, shed=False)
    svc = manual_service(admission=pol, max_queue=1)
    svc.submit(make_query(3.0, 0.9, **KW))
    w1 = _occ_total(svc)
    assert w1 > 0.0
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(5.0, 0.9, **KW))
    assert exc.value.reason == "queue_full"
    assert _occ_total(svc) == pytest.approx(w1)
    svc.flush()
    assert _occ_total(svc) == pytest.approx(0.0)
    svc.close()


def test_concurrent_submits_never_overshoot_budget():
    """Admit + acquire is atomic: racing submits cannot jointly push
    the weighted occupancy past the budget."""
    w = predicted_work((3.0, 0.9, 0.2))
    pol = AdmissionPolicy(max_work=3.05 * w, shed=False)
    svc = manual_service(admission=pol, max_queue=64)
    n = 8
    barrier = threading.Barrier(n)
    rejected = []

    def race(i):
        barrier.wait()
        try:
            svc.submit(make_query(2.0 + 0.1 * i, 0.9, **KW))
        except Overloaded:
            rejected.append(i)
    threads = [threading.Thread(target=race, args=(i,))
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert _occ_total(svc) <= pol.max_work + 1e-9
    assert len(rejected) >= n - 3          # ~3 weights fit the budget
    svc.close(drain=True)
    assert _occ_total(svc) == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# Degraded answers.
# ---------------------------------------------------------------------------

def test_degraded_answer_is_tagged_and_never_cached():
    pol = AdmissionPolicy(degraded_pressure=0.0, degraded_distance=0.5)
    svc = manual_service(admission=pol, donor_cutoff=0.5)
    donor = svc.query(3.0, 0.6, **KW)
    q = make_query(3.0, 0.65, degraded_ok=True, **KW)
    fut = svc.submit(q)
    assert fut.done()                        # store read, no queueing
    res = fut.result()
    assert res.path == "degraded"
    assert res.quality == "degraded_neighbor"
    assert res.donor_key == donor.key
    assert res.degraded_distance == pytest.approx(0.05 / 0.9)
    # the donor's NUMBERS, the query's OWN key — and never cached as the
    # query's exact answer: a later same-key query still solves
    assert res.r_star == donor.r_star and res.key == q.key()
    assert svc.store.get(q.key()) is None
    later = svc.query(3.0, 0.65, **KW)
    assert later.path in ("near", "cold") and later.quality == "exact"
    assert svc.metrics.snapshot()["serve_degraded_rate"] > 0
    svc.close()


def test_degraded_declines_beyond_distance_budget_and_without_consent():
    pol = AdmissionPolicy(degraded_pressure=0.0, degraded_distance=0.01)
    svc = manual_service(admission=pol)
    svc.query(3.0, 0.6, **KW)
    # outside the distance budget -> falls through to a normal queue
    fut = svc.submit(make_query(1.0, 0.0, degraded_ok=True, **KW))
    assert not fut.done()
    svc.flush()
    assert fut.result(0).quality == "exact"
    # no consent -> never degraded, even in range
    pol2 = AdmissionPolicy(degraded_pressure=0.0, degraded_distance=0.5)
    svc2 = manual_service(admission=pol2)
    svc2.query(3.0, 0.6, **KW)
    fut2 = svc2.submit(make_query(3.0, 0.65, **KW))
    assert not fut2.done()
    svc2.flush()
    assert fut2.result(0).quality == "exact"
    svc.close()
    svc2.close()


def test_degraded_gated_by_pressure_threshold():
    pol = AdmissionPolicy(max_work=2.0, degraded_pressure=0.3,
                          degraded_distance=0.5)
    svc = manual_service(admission=pol, donor_cutoff=0.5)
    svc.query(3.0, 0.6, **KW)
    # idle service: a degraded_ok query queues normally
    fut = svc.submit(make_query(3.0, 0.65, degraded_ok=True, **KW))
    assert not fut.done()
    # pressure past the threshold: the same query degrades
    fut2 = svc.submit(make_query(3.0, 0.55, degraded_ok=True, **KW))
    assert fut2.done()
    assert fut2.result().quality == "degraded_neighbor"
    svc.close(drain=True)


def test_degraded_require_certified_skips_uncertified_donors():
    pol = AdmissionPolicy(degraded_pressure=0.0, degraded_distance=0.5,
                          degraded_require_certified=True)
    svc = manual_service(admission=pol)
    svc.query(3.0, 0.6, **KW)                # UNCERTIFIED store entry
    fut = svc.submit(make_query(3.0, 0.65, degraded_ok=True, **KW))
    assert not fut.done()                    # no certified donor
    svc.close(drain=True)


# ---------------------------------------------------------------------------
# Regional circuit breakers.
# ---------------------------------------------------------------------------

def breaker_service(clk, **pol_over):
    pol = AdmissionPolicy(breaker_failures=2, breaker_cooldown_s=1.0,
                          **pol_over)
    return manual_service(admission=pol, clock=clk,
                          inject_fault_mode="nan")


def fail_once(svc, crra=1.0, rho=0.3):
    fut = svc.submit(make_query(crra, rho, fault_iter=0, **KW))
    svc.flush()
    with pytest.raises(EquilibriumSolveFailed):
        fut.result(0)


def test_breaker_opens_fast_fails_probes_and_closes():
    clk = ManualClock()
    svc = breaker_service(clk)
    region = svc.breaker.region_key(
        (1.0, 0.3, 0.2), make_query(1.0, 0.3, **KW).group())
    fail_once(svc)
    assert svc.breaker.state(region) == "closed"    # 1 < K
    fail_once(svc)
    assert svc.breaker.state(region) == "open"      # K = 2
    # fast-fail, typed, with the probe schedule in the payload
    with pytest.raises(CircuitOpen) as exc:
        svc.submit(make_query(1.0, 0.3, **KW))
    assert exc.value.status == CIRCUIT_OPEN
    assert exc.value.region == region
    assert exc.value.retry_after_s == pytest.approx(1.0)
    # a NEIGHBOR in the same quantized region fast-fails too
    assert svc.breaker.region_key(
        (0.9, 0.32, 0.2), make_query(1.0, 0.3, **KW).group()) == region
    with pytest.raises(CircuitOpen):
        svc.submit(make_query(0.9, 0.32, **KW))
    # ... but a far cell in another region is untouched
    far = svc.submit(make_query(5.0, 0.9, **KW))
    svc.flush()
    assert not is_failure(far.result(0).status)
    # half-open: exactly one probe at/after the cooldown
    clk.advance(1.0)
    probe = svc.submit(make_query(1.0, 0.3, **KW))
    assert svc.breaker.state(region) == "half_open"
    with pytest.raises(CircuitOpen):             # concurrent query still
        svc.submit(make_query(1.0, 0.31, **KW))  # fast-fails mid-probe
    svc.flush()
    assert not is_failure(probe.result(0).status)
    assert svc.breaker.state(region) == "closed"
    # closed: normal service resumes
    ok = svc.submit(make_query(1.0, 0.32, **KW))
    svc.flush()
    assert not is_failure(ok.result(0).status)
    snap = svc.metrics.snapshot()
    assert snap["serve_breaker_opens"] == 1
    assert snap["serve_breaker_probes"] == 1
    assert snap["serve_breaker_closes"] == 1
    assert snap["serve_circuit_rejects"] == 3
    svc.close()


def test_failed_probe_reopens_with_doubled_cooldown():
    clk = ManualClock()
    svc = breaker_service(clk)
    region = svc.breaker.region_key(
        (1.0, 0.3, 0.2), make_query(1.0, 0.3, **KW).group())
    fail_once(svc)
    fail_once(svc)
    clk.advance(1.0)
    fail_once(svc)                        # the probe itself fails
    assert svc.breaker.state(region) == "open"
    assert svc.breaker.retry_after(region, clk()) == pytest.approx(2.0)
    clk.advance(1.0)                      # inside the doubled cooldown
    with pytest.raises(CircuitOpen):
        svc.submit(make_query(1.0, 0.3, **KW))
    clk.advance(1.0)                      # cooldown elapsed -> probe
    probe = svc.submit(make_query(1.0, 0.3, **KW))
    svc.flush()
    assert not is_failure(probe.result(0).status)
    assert svc.breaker.state(region) == "closed"
    assert svc.metrics.snapshot()["serve_breaker_reopens"] == 1
    svc.close()


def test_shed_probe_reopens_the_probe_window():
    """A probe displaced by shedding must not wedge the region in
    half-open: the breaker returns to OPEN and the next due admit
    probes again."""
    clk = ManualClock()
    svc = breaker_service(clk, max_work=1.0,
                          class_shares=(1.0, 1.0, 1.0), shed=True)
    region = svc.breaker.region_key(
        (1.0, 0.3, 0.2), make_query(1.0, 0.3, **KW).group())
    fail_once(svc)
    fail_once(svc)
    clk.advance(1.0)
    probe = svc.submit(make_query(1.0, 0.3,
                                  priority=Priority.SPECULATIVE, **KW))
    assert svc.breaker.state(region) == "half_open"
    svc.submit(make_query(5.0, 0.9, priority=Priority.INTERACTIVE, **KW))
    with pytest.raises(LoadShed):
        probe.result(0)
    assert svc.breaker.state(region) == "open"
    svc.flush()                       # drain the displacing interactive
    probe2 = svc.submit(make_query(1.0, 0.3, **KW))   # re-probe, due now
    svc.flush()
    assert not is_failure(probe2.result(0).status)
    assert svc.breaker.state(region) == "closed"
    svc.close()


def test_probe_rejected_by_admission_reopens_the_probe_window():
    """A half-open probe that the ADMISSION layer rejects (budget or
    deadline) must not wedge the region: the probing flag is released
    with the raise, so the next due admit probes again — a leaked flag
    would pin the breaker open forever."""
    clk = ManualClock()
    w_probe = predicted_work((1.0, 0.3, 0.2))
    w_far = predicted_work((5.0, 0.9, 0.2))
    svc = breaker_service(clk, shed=False,
                          max_work=max(w_probe, w_far)
                          + 0.5 * min(w_probe, w_far))
    region = svc.breaker.region_key(
        (1.0, 0.3, 0.2), make_query(1.0, 0.3, **KW).group())
    fail_once(svc)
    fail_once(svc)
    assert svc.breaker.state(region) == "open"
    svc.submit(make_query(5.0, 0.9, **KW))     # saturate the budget
    clk.advance(1.0)                           # cooldown elapsed
    with pytest.raises(Overloaded):            # probe verdict, then the
        svc.submit(make_query(1.0, 0.3, **KW))  # class budget rejects
    assert svc.breaker.state(region) == "open", \
        "rejected probe wedged the region half-open"
    # the deadline-unmeetable rejection must release it too
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(1.0, 0.3, **KW), deadline=1e-9)
    assert exc.value.reason in ("deadline_unmeetable", "class_budget")
    assert svc.breaker.state(region) == "open"
    svc.flush()                                # free the budget
    probe = svc.submit(make_query(1.0, 0.3, **KW))
    assert svc.breaker.state(region) == "half_open"
    svc.flush()
    assert not is_failure(probe.result(0).status)
    assert svc.breaker.state(region) == "closed"
    svc.close()


def test_breaker_unit_state_machine():
    """Host-only breaker contract, no solves: deterministic schedule."""
    b = CircuitBreaker(failures=3, cooldown_s=2.0, backoff_cap=4)
    r = b.region_key((3.0, 0.6, 0.2), 7)
    assert b.admit(r, 0.0) == "ok"
    assert b.record_failure(r, 0.0) is None
    assert b.record_failure(r, 0.1) is None
    assert b.record_success(r, 0.2) is None          # resets the count
    assert b.record_failure(r, 0.3) is None
    assert b.record_failure(r, 0.4) is None
    assert b.record_failure(r, 0.5) == "opened"
    assert b.admit(r, 0.6) == "open"
    assert b.retry_after(r, 0.6) == pytest.approx(1.9)
    assert b.admit(r, 2.5) == "probe"
    assert b.admit(r, 2.6) == "open"                 # one probe only
    assert b.record_failure(r, 2.7) == "reopened"
    assert b.retry_after(r, 2.7) == pytest.approx(4.0)
    assert b.admit(r, 6.7) == "probe"
    assert b.record_success(r, 6.8) == "closed"
    assert b.admit(r, 6.9) == "ok"
    kinds = [w for _, _, w in b.transitions()]
    assert kinds == ["opened", "probe", "reopened", "probe", "closed"]


# ---------------------------------------------------------------------------
# Event contract: every typed overload outcome journals exactly once.
# ---------------------------------------------------------------------------

def test_every_overload_path_emits_exactly_one_typed_event(tmp_path):
    def journal(name):
        return str(tmp_path / f"{name}.jsonl")

    # OVERLOADED (class budget)
    jp = journal("overloaded")
    svc = manual_service(admission=AdmissionPolicy(max_work=1.0,
                                                   shed=False),
                         obs=ObsConfig(enabled=True, journal_path=jp))
    svc.submit(make_query(3.0, 0.6, **KW))
    with pytest.raises(Overloaded):
        svc.submit(make_query(1.0, 0.0, **KW))
    svc.close(drain=True)
    evs = read_journal(jp, event="OVERLOADED")
    assert len(evs) == 1 and evs[0]["reason"] == "class_budget"

    # LOAD_SHED
    jp = journal("shed")
    svc = manual_service(
        admission=AdmissionPolicy(max_work=1.0,
                                  class_shares=(1.0, 1.0, 1.0)),
        obs=ObsConfig(enabled=True, journal_path=jp))
    shed_fut = svc.submit(make_query(3.0, 0.6,
                                     priority=Priority.SPECULATIVE, **KW))
    svc.submit(make_query(1.0, 0.0, priority=Priority.INTERACTIVE, **KW))
    with pytest.raises(LoadShed):
        shed_fut.result(0)
    svc.close(drain=True)
    evs = read_journal(jp, event="LOAD_SHED")
    assert len(evs) == 1 and evs[0]["priority"] == Priority.SPECULATIVE

    # DEGRADED_ANSWER
    jp = journal("degraded")
    svc = manual_service(
        admission=AdmissionPolicy(degraded_pressure=0.0,
                                  degraded_distance=0.5),
        obs=ObsConfig(enabled=True, journal_path=jp))
    svc.query(3.0, 0.6, **KW)
    assert svc.submit(
        make_query(3.0, 0.65, degraded_ok=True, **KW)).result(0)
    svc.close(drain=True)
    evs = read_journal(jp, event="DEGRADED_ANSWER")
    assert len(evs) == 1 and "donor_key" in evs[0]

    # DEADLINE_EXCEEDED at submit (where="submit")
    jp = journal("deadline")
    svc = manual_service(obs=ObsConfig(enabled=True, journal_path=jp))
    with pytest.raises(DeadlineExceeded):
        svc.submit(make_query(3.0, 0.6, **KW), deadline=0.0)
    svc.close(drain=True)
    evs = read_journal(jp, event="DEADLINE_EXCEEDED")
    assert len(evs) == 1 and evs[0]["where"] == "submit"

    # breaker family: OPEN x1, REJECT x1, PROBE x1, CLOSE x1
    jp = journal("breaker")
    clk = ManualClock()
    svc = manual_service(
        admission=AdmissionPolicy(breaker_failures=1,
                                  breaker_cooldown_s=1.0),
        clock=clk, inject_fault_mode="nan",
        obs=ObsConfig(enabled=True, journal_path=jp))
    fail_once(svc)
    with pytest.raises(CircuitOpen):
        svc.submit(make_query(1.0, 0.3, **KW))
    clk.advance(1.0)
    probe = svc.submit(make_query(1.0, 0.3, **KW))
    svc.flush()
    assert not is_failure(probe.result(0).status)
    svc.close(drain=True)
    for etype, n in (("CIRCUIT_OPEN", 1), ("CIRCUIT_REJECT", 1),
                     ("CIRCUIT_PROBE", 1), ("CIRCUIT_CLOSE", 1)):
        assert len(read_journal(jp, event=etype)) == n, etype


# ---------------------------------------------------------------------------
# Bit-identity with admission enabled (the PR 4 contract survives).
# ---------------------------------------------------------------------------

def test_unsaturated_admission_serves_bit_identical_results():
    """Admission control gates the QUEUE, never the numbers: below
    saturation, every served result equals the direct single-cell
    reference launch bit for bit — the PR 4 packing-independence
    contract with the overload layer enabled."""
    svc = manual_service(admission=AdmissionPolicy(), donor_cutoff=0.5)
    ra = svc.query(3.0, 0.6, **KW)
    fb = svc.submit(make_query(3.0, 0.65, **KW))    # near
    fc = svc.submit(make_query(1.0, 0.0, **KW))     # cold
    fd = svc.submit(make_query(3.0, 0.55, **KW))    # near
    assert svc.flush() == 1
    rb, rc, rd = fb.result(0), fc.result(0), fd.result(0)
    assert rb.path == "near" and rc.path == "cold" and rd.path == "near"
    for res, q in ((ra, make_query(3.0, 0.6, **KW)),
                   (rb, make_query(3.0, 0.65, **KW)),
                   (rc, make_query(1.0, 0.0, **KW)),
                   (rd, make_query(3.0, 0.55, **KW))):
        ref = svc.reference_solve(q, bracket_init=res.bracket_init)
        assert_rows_equal(res, ref)
        assert res.quality == "exact"
    svc.close()


def test_unsaturated_admission_matches_no_admission_bits():
    """The same queries through an admission-enabled and a plain service
    produce identical bits (and identical paths)."""
    plain = manual_service(donor_cutoff=0.5)
    gated = manual_service(admission=AdmissionPolicy(), donor_cutoff=0.5)
    for svc in (plain, gated):
        svc.query(3.0, 0.6, **KW)
    results = {}
    for name, svc in (("plain", plain), ("gated", gated)):
        futs = [svc.submit(make_query(c, r, **KW))
                for c, r in ((3.0, 0.65), (1.0, 0.0), (5.0, 0.9))]
        svc.flush()
        results[name] = [f.result(0) for f in futs]
    for a, b in zip(results["plain"], results["gated"]):
        assert_rows_equal(a, b)
        assert a.path == b.path
    plain.close()
    gated.close()


# ---------------------------------------------------------------------------
# Metrics satellites.
# ---------------------------------------------------------------------------

def test_queue_depth_sampled_at_pop_and_histogrammed():
    svc = manual_service()
    for rho in (0.0, 0.3, 0.6):
        svc.submit(make_query(1.0, rho, **KW))
    pre = svc.metrics.depth_hist.count
    assert pre == 3                         # one sample per submit
    svc.flush()
    assert svc.metrics.depth_hist.count == pre + 1   # pre-pop sample
    snap = svc.metrics.snapshot()
    assert snap["serve_queue_depth_peak"] == 3
    assert snap["serve_queue_depth_p50"] is not None
    assert snap["serve_queue_depth_p99"] is not None
    svc.close()


def test_depth_histogram_reaches_obs_registry(tmp_path):
    obs = ObsConfig(enabled=True)
    svc = manual_service(obs=obs)
    svc.submit(make_query(3.0, 0.6, **KW))
    svc.flush()
    reg = svc._obs.registry
    hist = reg.histogram("aiyagari_serve_queue_depth")
    assert hist.count >= 2                  # submit + pop samples
    svc.close()


def test_make_query_validates_priority():
    with pytest.raises(ValueError):
        make_query(3.0, 0.6, priority=7, **KW)
    q = make_query(3.0, 0.6, priority=Priority.BATCH, degraded_ok=True,
                   **KW)
    # overload knobs never move the solution address
    assert q.key() == make_query(3.0, 0.6, **KW).key()


# ---------------------------------------------------------------------------
# Threaded overload soak (slow): no future ever hangs unresolved.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_threaded_overload_soak_every_future_resolves():
    """4 threads x 40 submits against a tiny admission budget through a
    LIVE worker: every single future reaches a typed outcome — a
    ServedResult, or Overloaded/LoadShed/CircuitOpen raised at submit,
    or a typed failure on the future.  Zero hangs, zero bare errors."""
    rng = np.random.default_rng(99)
    lattice = [(c, r) for c in (1.0, 3.0) for r in (0.0, 0.3, 0.6, 0.9)]
    picks = rng.integers(0, len(lattice), 160)
    prios = rng.integers(0, 3, 160)
    pol = AdmissionPolicy(max_work=3.0, est_batch_s=0.01)
    svc = EquilibriumService(max_batch=4, max_wait_s=0.002,
                             max_queue=16, ladder=(1, 2, 4),
                             admission=pol)
    outcomes = [None] * len(picks)

    def submitter(tid):
        for i in range(tid, len(picks), 4):
            c, r = lattice[int(picks[i])]
            try:
                fut = svc.submit(make_query(c, r, priority=int(prios[i]),
                                            **KW))
            except (Overloaded, CircuitOpen) as e:
                outcomes[i] = type(e).__name__
                continue
            try:
                res = fut.result(120)       # must NEVER hang
                outcomes[i] = f"served:{res.path}"
            except (LoadShed, DeadlineExceeded,
                    EquilibriumSolveFailed) as e:
                outcomes[i] = type(e).__name__

    threads = [threading.Thread(target=submitter, args=(t,))
               for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(300)
        assert not t.is_alive(), "submitter hung: a future never resolved"
    svc.close()
    assert all(o is not None for o in outcomes)
    served = sum(1 for o in outcomes if o.startswith("served:"))
    assert served > 0
    snap = svc.metrics.snapshot()
    assert snap["serve_requests"] + snap["serve_overloaded"] \
        + snap["serve_load_sheds"] + snap["serve_circuit_rejects"] \
        >= len(picks)
