"""FleetClient typed resilience (ISSUE 16): bounded deterministic
retry/backoff on an injectable clock, the 503 ``Retry-After``
header==payload repr pin across a REAL HTTP hop, per-request deadlines
raising typed ``DeadlineExceeded`` instead of sleeping past the budget,
and hedged reads — legal only for known-published fingerprints, first
answer wins, counted and journaled.
"""

import threading
import time

import pytest

from aiyagari_hark_tpu.serve.fleet import (
    FleetClient,
    FleetFront,
    FleetHTTPError,
    HedgePolicy,
    RetryPolicy,
)
from aiyagari_hark_tpu.serve.service import DeadlineExceeded, Overloaded

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)
CELL = (3.0, 0.6, 0.2)


# -- deterministic clock/sleep ----------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def _client(script, retry=None, hedge=None, clock=None, obs=None,
            urls=("http://stub-a", "http://stub-b")):
    """A FleetClient whose pool sweep is replaced by a scripted stub:
    each call pops the next entry — an exception instance to raise or a
    dict to return."""
    clock = clock if clock is not None else FakeClock()
    c = FleetClient(list(urls), retry=retry, hedge=hedge,
                    clock=clock, sleep=clock.sleep, obs=obs)
    calls = []

    def _scripted(payload, start):
        calls.append(start)
        step = script.pop(0)
        if isinstance(step, BaseException):
            raise step
        return dict(step)

    c._query_once = _scripted
    return c, clock, calls


def _err503(retry_after=None):
    payload = {"error": "Overloaded", "message": "queue full"}
    if retry_after is not None:
        payload["retry_after_s"] = retry_after
    return FleetHTTPError(503, payload, retry_after_s=retry_after)


# -- RetryPolicy schedule ----------------------------------------------------

def test_backoff_schedule_is_deterministic():
    p = RetryPolicy(max_attempts=4, base_s=0.05, multiplier=2.0,
                    max_backoff_s=2.0)
    assert [p.backoff_s(k) for k in range(4)] == [0.05, 0.1, 0.2, 0.4]
    # the server's Retry-After raises the wait but never beats the cap
    assert p.backoff_s(0, retry_after_s=0.7) == 0.7
    assert p.backoff_s(4, retry_after_s=0.7) == 0.8
    assert p.backoff_s(0, retry_after_s=10.0) == 2.0
    assert p.backoff_s(10) == 2.0


def test_retry_on_503_honors_retry_after():
    c, clock, _ = _client(
        [_err503(0.7), _err503(0.7), {"path": "hit"}],
        retry=RetryPolicy(max_attempts=4, base_s=0.05))
    res = c.query(CELL, KW)
    assert res == {"path": "hit"}
    # both waits raised to the server's estimate (0.05/0.1 < 0.7)
    assert clock.sleeps == [0.7, 0.7]


def test_retry_uses_own_schedule_without_retry_after():
    c, clock, _ = _client(
        [_err503(), _err503(), {"path": "hit"}],
        retry=RetryPolicy(max_attempts=4, base_s=0.05))
    assert c.query(CELL, KW) == {"path": "hit"}
    assert clock.sleeps == [0.05, 0.1]


def test_non_503_is_never_retried():
    c, clock, _ = _client(
        [FleetHTTPError(400, {"error": "BadRequest", "message": "x"})],
        retry=RetryPolicy())
    with pytest.raises(FleetHTTPError) as exc:
        c.query(CELL, KW)
    assert exc.value.code == 400
    assert clock.sleeps == []


def test_retry_exhaustion_raises_the_last_error():
    c, clock, _ = _client([_err503(), _err503(), _err503()],
                          retry=RetryPolicy(max_attempts=3, base_s=0.05))
    with pytest.raises(FleetHTTPError) as exc:
        c.query(CELL, KW)
    assert exc.value.code == 503
    assert clock.sleeps == [0.05, 0.1]         # attempts-1 waits


def test_connection_errors_retried_then_propagate():
    c, clock, _ = _client(
        [ConnectionError("down"), ConnectionError("down"),
         {"path": "hit"}],
        retry=RetryPolicy(max_attempts=4, base_s=0.05))
    assert c.query(CELL, KW) == {"path": "hit"}
    assert clock.sleeps == [0.05, 0.1]

    c2, clock2, _ = _client([ConnectionError("down")] * 2,
                            retry=RetryPolicy(max_attempts=2, base_s=0.05))
    with pytest.raises(ConnectionError):
        c2.query(CELL, KW)
    assert clock2.sleeps == [0.05]


def test_without_retry_policy_behavior_is_unchanged():
    c, clock, _ = _client([_err503(1.0)])
    with pytest.raises(FleetHTTPError):
        c.query(CELL, KW)
    assert clock.sleeps == []


def test_deadline_raises_typed_instead_of_oversleeping():
    # the budget cannot cover the next wait: typed DeadlineExceeded, on
    # the INJECTED clock, without sleeping past the limit
    c, clock, _ = _client([_err503()] * 4,
                          retry=RetryPolicy(max_attempts=4, base_s=1.0))
    with pytest.raises(DeadlineExceeded):
        c.query(CELL, KW, deadline_s=0.5)
    assert clock.sleeps == []                  # never slept past the budget

    # a budget that covers one wait retries once, then raises typed
    c2, clock2, _ = _client([_err503()] * 4,
                            retry=RetryPolicy(max_attempts=4, base_s=1.0,
                                              multiplier=2.0))
    with pytest.raises(DeadlineExceeded):
        c2.query(CELL, KW, deadline_s=1.5)
    assert clock2.sleeps == [1.0]


# -- hedged reads ------------------------------------------------------------

class _RecObs:
    def __init__(self):
        self.events = []

    def event(self, etype, **fields):
        self.events.append((etype, dict(fields)))

    def of(self, etype):
        return [f for t, f in self.events if t == etype]


def test_cold_miss_never_hedges():
    # the fingerprint was never seen answered: even with a hedge policy
    # attached the query runs the plain single sweep
    obs = _RecObs()
    c, _, calls = _client([{"path": "cold"}],
                          hedge=HedgePolicy(delay_s=0.001), obs=obs)
    assert c.query(CELL, KW) == {"path": "cold"}
    assert calls == [0]                        # one sweep, no hedge thread
    assert c.hedge_counts() == {"issued": 0, "won": 0}
    assert obs.of("FLEET_HEDGE_ISSUED") == []


def test_hedge_issued_after_delay_and_hedge_wins():
    obs = _RecObs()
    release = threading.Event()

    def slow_primary(payload, start):
        release.wait(5.0)                      # the sick worker
        return {"path": "hit", "who": "primary"}

    def fast_hedge(payload, start):
        return {"path": "hit", "who": "hedge"}

    c = FleetClient(["http://a", "http://b"],
                    hedge=HedgePolicy(delay_s=0.02), obs=obs)
    calls = []

    def _scripted(payload, start):
        calls.append(start)
        return (slow_primary if start == 0 else fast_hedge)(payload,
                                                            start)

    c._query_once = _scripted
    c.note_published("aiyagari", CELL)         # hedge-legal
    res = c.query(CELL, KW)
    assert res["who"] == "hedge"               # first answer won
    assert c.hedge_counts() == {"issued": 1, "won": 1}
    assert len(obs.of("FLEET_HEDGE_ISSUED")) == 1
    assert len(obs.of("FLEET_HEDGE_WON")) == 1
    assert sorted(calls) == [0, 1]             # primary + hedge, distinct
    release.set()


def test_fast_primary_wins_without_hedging():
    obs = _RecObs()
    c, _, calls = _client([{"path": "hit"}],
                          hedge=HedgePolicy(delay_s=5.0), obs=obs)
    c.note_published("aiyagari", CELL)
    assert c.query(CELL, KW) == {"path": "hit"}
    assert c.hedge_counts() == {"issued": 0, "won": 0}
    assert obs.of("FLEET_HEDGE_ISSUED") == []


def test_hedge_requires_two_workers():
    c, _, calls = _client([{"path": "hit"}],
                          hedge=HedgePolicy(delay_s=0.0),
                          urls=("http://only",))
    c.note_published("aiyagari", CELL)
    assert c.query(CELL, KW) == {"path": "hit"}
    assert c.hedge_counts() == {"issued": 0, "won": 0}


def test_hedge_delay_derives_from_p99():
    c = FleetClient(["http://a", "http://b"],
                    hedge=HedgePolicy(min_delay_s=0.01))
    assert c._hedge_delay_s() == 0.01          # no history: the floor
    c._lat_s = [0.001 * k for k in range(1, 101)]
    assert c._hedge_delay_s() == pytest.approx(0.099)  # ~p99 of history
    c._lat_s = [0.0001]
    assert c._hedge_delay_s() == 0.01          # floored


# -- the Retry-After pin across a REAL HTTP hop -----------------------------

class _ImmediateFuture:
    def __init__(self, res):
        self._res = res

    def result(self, timeout=None):
        return self._res


class _OverloadedService:
    """Minimal service stub for FleetFront: every submit refuses with a
    fractional retry-after, exercising the 503 + Retry-After path."""

    def __init__(self, est_wait_s):
        self.est_wait_s = est_wait_s

    def submit(self, q, deadline=None):
        raise Overloaded(cell=(q.crra, q.labor_ar, q.labor_sd), key=0,
                         depth=3, max_queue=3,
                         est_wait_s=self.est_wait_s, reason="queue_full")


def test_retry_after_header_equals_payload_bit_exactly():
    # a fractional, repr-unfriendly float: 0.1 + 0.2 = 0.30000000000000004
    est = 0.1 + 0.2
    front = FleetFront(_OverloadedService(est)).start()
    try:
        import urllib.error
        import urllib.request
        import json as _json

        body = _json.dumps({"cell": list(CELL), "kwargs": KW}).encode()
        req = urllib.request.Request(
            front.url + "/query", data=body,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=30.0)
        e = exc.value
        assert e.code == 503
        header = e.headers.get("Retry-After")
        payload = _json.loads(e.read().decode("utf-8"))
        assert payload["error"] == "Overloaded"
        # the pin: header string IS the repr of the payload float, so a
        # client honoring either sees the SAME wait, bit-exactly
        assert header == repr(est)
        assert float(header) == payload["retry_after_s"] == est

        # and the typed client surfaces it on the error object
        client = FleetClient([front.url])
        with pytest.raises(FleetHTTPError) as cexc:
            client.query(CELL, KW)
        assert cexc.value.code == 503
        assert cexc.value.retry_after_s == est
        assert cexc.value.payload["retry_after_s"] == est
    finally:
        front.stop()


def test_client_retries_through_a_real_503_front():
    # one REAL front that always refuses: the retrying client consumes
    # its schedule (waits raised to the server's Retry-After) and then
    # surfaces the typed 503
    front = FleetFront(_OverloadedService(0.01)).start()
    try:
        clock = FakeClock()
        client = FleetClient([front.url],
                             retry=RetryPolicy(max_attempts=3,
                                               base_s=0.005),
                             clock=clock, sleep=clock.sleep)
        with pytest.raises(FleetHTTPError) as exc:
            client.query(CELL, KW)
        assert exc.value.code == 503
        assert clock.sleeps == [0.01, 0.01]    # Retry-After > base sched
    finally:
        front.stop()
