"""Fleet serving tier (ISSUE 15): lease primitives, the store's
claim/publish election, the service's fleet gate and waiter path,
speculative neighbor prefetch, the admission EWMA cold-start seed, the
HTTP front's transport contract, and the fleet_* regression directions.

The two-PROCESS soak (racing writers over one disk tier) lives in
``tests/test_fleet_store.py``; the end-to-end multi-worker replay with
the SIGTERM drill is ``bench.py --fleet-smoke``.  This file pins the
mechanisms deterministically and in-process."""

import os
import threading
import time

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import ObsConfig
from aiyagari_hark_tpu.obs.journal import read_journal
from aiyagari_hark_tpu.scenarios.aiyagari import AIYAGARI_SCHEMA
from aiyagari_hark_tpu.serve import (
    AdmissionPolicy,
    EquilibriumService,
    FleetClient,
    FleetFront,
    FleetHTTPError,
    Overloaded,
    Priority,
    make_query,
)
from aiyagari_hark_tpu.serve.store import SolutionStore, make_solution
from aiyagari_hark_tpu.utils.checkpoint import (
    acquire_lease,
    break_stale_lease,
    lease_age_s,
    read_lease,
    release_lease,
)

# the suite-shared tiny-cell configuration (compiled executables reused
# across files)
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)
CELLS = [(s, r, 0.2) for s in (1.0, 3.0, 5.0)
         for r in (0.0, 0.3, 0.6, 0.9)]


def _row(seed: float = 0.01) -> np.ndarray:
    """A healthy synthetic packed row in the Aiyagari schema layout."""
    row = np.zeros(len(AIYAGARI_SCHEMA.fields))
    row[AIYAGARI_SCHEMA.idx(AIYAGARI_SCHEMA.root)] = seed
    return row


def _store(tmp_path, name="s", **over) -> SolutionStore:
    kw = dict(disk_path=str(tmp_path / "shared"), shared=True,
              lease_ttl_s=5.0)
    kw.update(over)
    return SolutionStore(owner=name, **kw)


# ---------------------------------------------------------------------------
# Lease primitives (utils.checkpoint).
# ---------------------------------------------------------------------------

def test_lease_exclusive_create_and_release(tmp_path):
    path = str(tmp_path / "k.lease")
    assert acquire_lease(path, owner="a")
    assert not acquire_lease(path, owner="b")   # loser
    assert read_lease(path) == {"owner": "a"}
    assert lease_age_s(path) >= 0.0
    assert release_lease(path)
    assert not release_lease(path)              # idempotent
    assert read_lease(path) is None
    assert lease_age_s(path) is None


def test_break_stale_lease_respects_ttl(tmp_path):
    path = str(tmp_path / "k.lease")
    acquire_lease(path, owner="a")
    assert not break_stale_lease(path, ttl_s=60.0)   # fresh
    old = time.time() - 120.0
    os.utime(path, (old, old))
    assert break_stale_lease(path, ttl_s=60.0)       # stale -> removed
    assert not os.path.exists(path)
    assert not break_stale_lease(path, ttl_s=60.0)   # already gone


# ---------------------------------------------------------------------------
# Store claim / publish election.
# ---------------------------------------------------------------------------

def test_claim_election_and_publish_visibility(tmp_path):
    a = _store(tmp_path, "A")
    b = _store(tmp_path, "B")
    sol = make_solution((3.0, 0.6, 0.2), _row(0.0123), group=7, key=42)
    assert a.claim(42) == "won"
    assert b.claim(42) == "lost"
    assert a.held_leases() == [42]
    a.publish(sol)
    assert a.held_leases() == []
    assert a.lease_files() == []
    # the loser claims again: published, and get() probes the disk for
    # a key its index never saw
    assert b.claim(42) == "published"
    got = b.get(42)
    assert got is not None
    assert float(got.root) == 0.0123
    assert np.array_equal(np.asarray(got.packed), _row(0.0123))
    assert b.fleet_counts()["fleet_claims_lost"] == 1
    assert a.fleet_counts()["fleet_publishes"] == 1


def test_release_without_publish_reopens_election(tmp_path):
    a = _store(tmp_path, "A")
    b = _store(tmp_path, "B")
    assert a.claim(7) == "won"
    a.release(7)                      # failed solve: abandon
    assert b.claim(7) == "won"        # immediately claimable again
    b.release(7)


def test_stale_lease_reclaim_and_gc(tmp_path):
    """A crashed winner's lease (no heartbeat) is broken past the TTL —
    by a claimant and by the end-of-run sweep."""
    b = _store(tmp_path, "B", lease_ttl_s=1.0)
    # a "crashed" owner: a raw lease file nobody heartbeats, backdated
    dead = os.path.join(str(tmp_path / "shared"), "lease_feedbeef.lease")
    acquire_lease(dead, owner="dead")
    old = time.time() - 10.0
    os.utime(dead, (old, old))
    assert b.gc_stale_leases() == 1
    assert b.fleet_counts()["fleet_lease_reclaims"] == 1
    # and through the claim path: stale break + win in one call
    lease = b._lease_file(9)
    acquire_lease(lease, owner="dead")
    os.utime(lease, (old, old))
    assert b.claim(9) == "won"
    assert b.fleet_counts()["fleet_lease_reclaims"] == 2
    b.release(9)


def test_heartbeat_keeps_live_claim_from_being_stolen(tmp_path):
    """The lease heartbeat (mtime refresh at ttl/4): a LIVE winner whose
    solve outlasts the TTL must not get its claim broken — staleness
    means 'owner stopped beating', never 'solve is slow'."""
    a = _store(tmp_path, "A", lease_ttl_s=0.4)
    b = _store(tmp_path, "B", lease_ttl_s=0.4)
    assert a.claim(5) == "won"
    time.sleep(1.0)                   # 2.5x the TTL
    assert not b.lease_stale(5)       # heartbeat refreshed the mtime
    assert b.claim(5) == "lost"
    assert b.fleet_counts()["fleet_lease_reclaims"] == 0
    a.release(5)


def test_claim_events_journaled(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    from aiyagari_hark_tpu.obs.runtime import build_obs

    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    a = _store(tmp_path, "A", obs=obs)
    a.claim(1)
    a.publish(make_solution((1.0, 0.0, 0.2), _row(), group=1, key=1),
              speculative=True, seed=(0.0, 0.05, 3))
    obs.close()
    assert len(read_journal(jp, event="FLEET_CLAIM")) == 1
    pub = read_journal(jp, event="FLEET_PUBLISH")
    assert len(pub) == 1
    assert pub[0]["speculative"] is True
    assert pub[0]["seed"] == [0.0, 0.05, 3]


# ---------------------------------------------------------------------------
# Service fleet gate: dedup, remote hit, waiter resolution.
# ---------------------------------------------------------------------------

def _manual(store=None, **over):
    kw = dict(start_worker=False, max_batch=4, max_wait_s=60.0,
              ladder=(1, 2, 4))
    kw.update(over)
    return EquilibriumService(store=store, **kw)


def test_fleet_in_batch_dedup_single_publish(tmp_path):
    """Two same-fingerprint submits in one flush ride ONE lane: one
    claim, one solve, one publish; both futures resolve identically."""
    svc = _manual(_store(tmp_path, "A"))
    f1 = svc.submit(make_query(5.0, 0.0, **KW))
    f2 = svc.submit(make_query(5.0, 0.0, **KW))
    svc.flush()
    r1, r2 = f1.result(0), f2.result(0)
    assert (r1.r_star, r1.capital, r1.status) == (r2.r_star, r2.capital,
                                                  r2.status)
    assert svc.store.fleet_counts()["fleet_publishes"] == 1
    assert svc.store.lease_files() == []
    svc.close()


def test_fleet_remote_publish_served_as_hit(tmp_path):
    """Worker B's miss on a fingerprint worker A already published is
    served from the shared tier — bit-identical, no second solve."""
    a = _manual(_store(tmp_path, "A"))
    ra = a.query(3.0, 0.6, **KW)
    b = _manual(_store(tmp_path, "B"))
    fb = b.submit(make_query(3.0, 0.6, **KW))
    if not fb.done():
        b.flush()
    rb = fb.result(0)
    assert rb.path == "hit"
    assert (rb.r_star, rb.capital, rb.labor, rb.status) == (
        ra.r_star, ra.capital, ra.labor, ra.status)
    assert b.store.fleet_counts()["fleet_publishes"] == 0
    a.close()
    b.close()


def test_fleet_waiter_serves_winner_publish(tmp_path):
    """The claim-loser path: B's flush blocks on A's in-flight claim
    and serves A's publish the moment it lands (loser-serves-winner)."""
    a_store = _store(tmp_path, "A")
    b = _manual(_store(tmp_path, "B"), fleet_poll_s=0.01)
    q = make_query(1.0, 0.3, **KW)
    assert a_store.claim(q.key()) == "won"     # A holds the election
    fb = b.submit(q)
    done = threading.Event()

    def _flush():
        b.flush()
        done.set()

    t = threading.Thread(target=_flush)
    t.start()
    time.sleep(0.3)
    assert not fb.done()                       # genuinely waiting
    # A "solves" and publishes the real row (via a reference service so
    # the bits are genuine)
    ref = _manual(SolutionStore(capacity=8))
    rr = ref.reference_solve(q)
    a_store.publish(make_solution(q.cell(),
                                  np.asarray(rr.values, dtype=np.float64),
                                  q.group(), q.key()))
    t.join(30.0)
    assert done.is_set()
    rb = fb.result(5.0)
    assert rb.path == "hit"
    assert rb.r_star == rr.r_star
    assert b.metrics.snapshot()["fleet_remote_hits"] == 1
    ref.close()
    b.close()


def test_fleet_waiter_takes_over_abandoned_claim(tmp_path):
    """A lease released WITHOUT a publish (the winner's solve failed or
    it crashed and was reclaimed): the waiter re-enqueues and the next
    flush re-runs the election — this process wins and solves."""
    a_store = _store(tmp_path, "A")
    b = _manual(_store(tmp_path, "B"), fleet_poll_s=0.01)
    q = make_query(1.0, 0.6, **KW)
    assert a_store.claim(q.key()) == "won"
    fb = b.submit(q)
    t = threading.Thread(target=b.flush)
    t.start()
    time.sleep(0.2)
    a_store.release(q.key())          # abandon: no publish
    t.join(30.0)
    assert not fb.done()              # re-enqueued, not yet solved
    b.flush()                         # election re-runs: B wins, solves
    rb = fb.result(5.0)
    assert rb.path in ("cold", "near")
    assert b.store.fleet_counts()["fleet_publishes"] == 1
    b.close()


# ---------------------------------------------------------------------------
# Speculative neighbor prefetch.
# ---------------------------------------------------------------------------

def test_prefetch_issues_speculative_neighbors(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    svc = _manual(prefetch_k=2, prefetch_cells=CELLS,
                  obs=ObsConfig(enabled=True, journal_path=jp))
    f = svc.submit(make_query(3.0, 0.6, **KW))
    # parent + 2 speculative neighbors queued
    assert svc.batcher.depth() == 3
    svc.flush()
    f.result(0)
    ev = read_journal(jp, event="PREFETCH_ISSUED")
    assert len(ev) == 2
    # nearest lattice neighbors of (3.0, 0.6) in normalized distance
    assert sorted(tuple(e["cell"]) for e in ev) == [
        (3.0, 0.3, 0.2), (3.0, 0.9, 0.2)]
    snap = svc.metrics.snapshot()
    assert snap["serve_prefetch_issued"] == 2
    # the neighbors are now exact hits; each converts exactly once
    assert svc.query(3.0, 0.3, **KW).path == "hit"
    assert svc.query(3.0, 0.3, **KW).path == "hit"
    assert svc.metrics.snapshot()["serve_prefetch_converted"] == 1
    svc.close()


def test_prefetch_skips_solved_and_never_recurses():
    svc = _manual(prefetch_k=8, prefetch_cells=CELLS[:4])
    for c in CELLS[:4]:
        svc.query(c[0], c[1], labor_sd=c[2], **KW)
    issued_before = svc.metrics.snapshot()["serve_prefetch_issued"]
    # everything solved: a fresh miss-free query issues nothing new
    svc.query(1.0, 0.0, **KW)
    assert svc.metrics.snapshot()["serve_prefetch_issued"] == issued_before
    svc.close()


def test_prefetch_sheddable_under_admission():
    """Prefetch rides Priority.SPECULATIVE: when the class budget has no
    room, the issue is SUPPRESSED (counted) — the triggering caller is
    never failed by its own prefetch, and interactive work is never
    displaced."""
    pol = AdmissionPolicy(max_work=0.9, shed=False, est_batch_s=0.01,
                          class_shares=(1.0, 0.5, 0.01))
    svc = _manual(prefetch_k=2, prefetch_cells=CELLS, admission=pol)
    f = svc.submit(make_query(3.0, 0.6, **KW))   # fills the budget
    snap = svc.metrics.snapshot()
    assert snap["serve_prefetch_suppressed"] == 2
    assert snap["serve_prefetch_issued"] == 0
    assert not f.done() or f.exception() is None
    svc.flush()
    assert f.result(0).path in ("cold", "near")
    svc.close()


def test_prefetch_requires_lattice():
    with pytest.raises(ValueError, match="prefetch_cells"):
        EquilibriumService(start_worker=False, prefetch_k=2)


def test_fleet_prefetch_publish_tagged_speculative(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    svc = _manual(_store(tmp_path, "A",
                         obs=None), prefetch_k=1, prefetch_cells=CELLS,
                  obs=ObsConfig(enabled=True, journal_path=jp))
    svc.query(3.0, 0.6, **KW)
    svc.flush()                        # drains the speculative pending
    svc.close()
    pub = read_journal(jp, event="FLEET_PUBLISH")
    spec = [e for e in pub if e.get("speculative")]
    assert len(pub) == 2 and len(spec) == 1
    assert all(e.get("seed") is not None for e in pub)


# ---------------------------------------------------------------------------
# Admission EWMA cold start (satellite).
# ---------------------------------------------------------------------------

def test_first_rejection_retry_after_is_finite_and_sane():
    """Before any batch has flushed there is no measured latency: the
    EWMA seeds from the first admission-checked query's own
    ``heuristic_cell_work`` predicted wall, so the FIRST ``Overloaded``
    carries a finite, solve-scaled retry-after instead of the batcher's
    millisecond ``max_wait_s``."""
    pol = AdmissionPolicy(max_work=1.0, shed=False)   # est_batch_s=None
    svc = _manual(max_wait_s=0.002, admission=pol)
    f = svc.submit(make_query(3.0, 0.6, **KW))
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(1.0, 0.0, **KW))
    e = exc.value
    assert np.isfinite(e.est_wait_s) and e.est_wait_s == e.retry_after_s
    # sane: at least one predicted batch wall (>> max_wait_s), bounded
    assert 0.002 < e.est_wait_s < 60.0
    svc.flush()
    f.result(0)
    svc.close()


def test_pinned_est_batch_s_still_takes_precedence():
    pol = AdmissionPolicy(max_work=1.0, shed=False, est_batch_s=0.5)
    svc = _manual(admission=pol)
    svc.submit(make_query(3.0, 0.6, **KW))
    with pytest.raises(Overloaded) as exc:
        svc.submit(make_query(1.0, 0.0, **KW))
    assert exc.value.est_wait_s == pytest.approx(0.5)
    svc.flush()
    svc.close()


# ---------------------------------------------------------------------------
# HTTP front transport contract.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def front_svc():
    svc = EquilibriumService(start_worker=True, max_batch=4,
                             max_wait_s=0.01, ladder=(1, 2, 4))
    front = FleetFront(svc).start()
    yield svc, front
    front.stop()
    svc.close()


def test_http_query_roundtrip_bit_exact(front_svc):
    svc, front = front_svc
    client = FleetClient([front.url], timeout=120.0)
    res = client.query((3.0, 0.6, 0.2), KW)
    assert res["path"] in ("cold", "near", "hit")
    ref = svc.reference_solve(
        make_query(3.0, 0.6, **KW),
        bracket_init=(None if res["bracket_init"] is None
                      else tuple(res["bracket_init"])))
    # the JSON hop is bit-exact: repr round-trip floats
    assert res["r_star"] == ref.r_star
    assert res["capital"] == ref.capital
    assert res["status"] == ref.status
    # replay: exact hit now
    res2 = client.query((3.0, 0.6, 0.2), KW)
    assert res2["path"] == "hit"
    assert res2["r_star"] == res["r_star"]


def test_http_metrics_fleet_and_healthz(front_svc):
    svc, front = front_svc
    client = FleetClient([front.url])
    health = client.get(front.url, "/healthz")
    assert health["ok"] is True
    # ISSUE 16: liveness now carries heartbeat/lease health
    assert set(health["heartbeat"]) >= {"thread_alive", "held", "beats",
                                        "lost_leases", "backend"}
    snap = client.get(front.url, "/metrics")
    assert snap["serve_requests"] >= 1
    fleet = client.get(front.url, "/fleet")
    assert set(fleet) >= {"owner", "published_keys", "prefetch_keys",
                          "held_leases", "store_known"}
    assert client.get(front.url, "/metrics") is not None


def test_http_typed_error_mapping(front_svc):
    svc, front = front_svc
    client = FleetClient([front.url])
    # expired deadline -> 504 with the typed payload
    with pytest.raises(FleetHTTPError) as exc:
        client.query((5.0, 0.9, 0.2), KW, deadline=-1.0)
    assert exc.value.code == 504
    assert exc.value.payload["error"] == "DeadlineExceeded"
    # unknown scenario -> 400 (make_query validates server-side)
    with pytest.raises(FleetHTTPError) as exc:
        client.query((3.0, 0.6, 0.2), KW, scenario="nope")
    assert exc.value.code == 400
    # 404 on an unknown path
    with pytest.raises(Exception):
        client.get(front.url, "/nope")


def test_http_client_fails_over_to_live_worker(front_svc):
    svc, front = front_svc
    dead_url = "http://127.0.0.1:9"     # discard port: refused
    client = FleetClient([dead_url, front.url])
    res = client.query((3.0, 0.6, 0.2), KW)   # prefers urls[0], fails over
    assert res["path"] == "hit"


# ---------------------------------------------------------------------------
# Regression-sentinel coverage for the fleet leg (CI satellite).
# ---------------------------------------------------------------------------

def test_direction_covers_fleet_smoke_record():
    """Every scalar the ``--fleet-smoke`` record emits resolves in the
    direction table, and the two load-bearing degradations — a dedup-
    ratio rise (duplicate solves) and a fleet p99 blow-up — flag
    REGRESSED from the first committed record."""
    from aiyagari_hark_tpu.obs.regress import (
        DOWN,
        NEUTRAL,
        OK,
        UP,
        direction_of_goodness,
        evaluate_history,
        flatten_record,
    )

    record = {
        "metric": "fleet_smoke", "backend": "cpu",
        "fleet_workers": 4, "fleet_cells": 12, "fleet_requests": 120,
        "fleet_wall_s": 50.0, "fleet_trace_digest": "ab",
        "fleet_served": 120, "fleet_served_hit": 113,
        "fleet_served_near": 4, "fleet_served_cold": 3,
        "fleet_unresolved": 0, "fleet_cold_solves": 12,
        "fleet_distinct_fingerprints": 12, "fleet_dedup_ratio": 1.0,
        "fleet_dedup_exact": True, "fleet_bit_identical": True,
        "fleet_value_mismatches": 0, "fleet_value_divergence": 0,
        "fleet_seeded_compares": 11,
        "fleet_prefetch_issued": 22, "fleet_prefetch_converted": 4,
        "fleet_remote_hits": 14, "fleet_claims_won": 12,
        "fleet_claims_lost": 7, "fleet_lease_reclaims": 0,
        "fleet_leases_leaked": 0, "fleet_drill_rc": 75,
        "fleet_drill_interrupted_typed": True,
        "fleet_hit_p50_ms": 3.2, "fleet_hit_p99_ms": 16000.0,
        "fleet_near_p50_ms": 15000.0, "fleet_cold_p50_ms": 21000.0,
        "fleet_cold_p99_ms": 22000.0,
        "fleet_sentinel_clean": True, "fleet_sentinel_worst": "OK",
    }
    for field in flatten_record(record):
        assert direction_of_goodness(field, strict=True) in (
            UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("fleet_dedup_ratio") == DOWN
    assert direction_of_goodness("fleet_leases_leaked") == DOWN
    assert direction_of_goodness("fleet_prefetch_converted") == UP
    assert direction_of_goodness("fleet_hit_p99_ms") == DOWN
    # the serve snapshot's new counters resolve too (they ride every
    # serve_* record via ServeMetrics.snapshot)
    for f in ("serve_prefetch_issued", "serve_prefetch_converted",
              "serve_prefetch_suppressed", "fleet_remote_hits",
              "fleet_claims_won", "fleet_claims_lost",
              "fleet_publishes", "fleet_lease_reclaims"):
        assert direction_of_goodness(f, strict=True) in (UP, DOWN,
                                                         NEUTRAL), f
    # synthetic-history grading: stable history clean; dedup-ratio rise
    # and p99 blow-up flag REGRESSED
    hist = [(f"r{i:02d}", dict(record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(record)
    worse["fleet_dedup_ratio"] = 1.5
    worse["fleet_hit_p99_ms"] = 40000.0
    flagged = [f.metric for f in
               evaluate_history(hist[:-1] + [("r99", worse)]).regressed()]
    assert "fleet_dedup_ratio" in flagged
    assert "fleet_hit_p99_ms" in flagged


def test_direction_covers_chaos_smoke_record():
    """Every scalar the ``--chaos-smoke`` record emits resolves in the
    direction table (ISSUE 16 CI satellite), availability degradation
    and duplicate recovery publishes grade as regressions, and the new
    fleet events are in the journal vocabulary."""
    from aiyagari_hark_tpu.obs.journal import EVENT_TYPES
    from aiyagari_hark_tpu.obs.regress import (
        DOWN,
        NEUTRAL,
        OK,
        UP,
        direction_of_goodness,
        evaluate_history,
        flatten_record,
    )

    record = {
        "metric": "chaos_smoke", "backend": "cpu",
        "chaos_workers": 4, "chaos_arrivals": 120,
        "chaos_wall_s": 200.0, "chaos_served": 118,
        "chaos_availability": 0.983, "chaos_unresolved": 0,
        "chaos_drills_injected": 5, "chaos_drills_detected": 5,
        "chaos_detect_all": True,
        "chaos_detected_torn_publish": 1, "chaos_detected_partition": 1,
        "chaos_detected_worker_kill": 1,
        "chaos_detected_heartbeat_stall": 1,
        "chaos_detected_clock_skew": 1,
        "chaos_dedup_ratio": 1.0, "chaos_dedup_exact": True,
        "chaos_traffic_dedup_exact": True,
        "chaos_recovery_dup_publishes": 0, "chaos_recovery_served": 6,
        "chaos_recovery_errors": 0, "chaos_leases_leaked": 0,
        "chaos_reclaims": 2, "chaos_joins": 1, "chaos_leaves": 1,
        "chaos_kills": 1, "chaos_hedges_issued": 3,
        "chaos_hedges_won": 1, "chaos_bit_identical": True,
        "chaos_value_mismatches": 0, "chaos_value_divergence": 0,
        "chaos_seeded_compares": 7, "chaos_churn_p99_ms": 9000.0,
        "chaos_hit_p50_ms": 4.0, "chaos_hit_p99_ms": 40.0,
        "chaos_sentinel_clean": True, "chaos_sentinel_worst": "OK",
    }
    for field in flatten_record(record):
        assert direction_of_goodness(field, strict=True) in (
            UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("chaos_availability") == UP
    assert direction_of_goodness("chaos_dedup_ratio") == DOWN
    assert direction_of_goodness("chaos_recovery_dup_publishes") == DOWN
    assert direction_of_goodness("chaos_leases_leaked") == DOWN
    assert direction_of_goodness("chaos_churn_p99_ms") == DOWN
    # availability collapse and a churn-p99 blow-up grade REGRESSED; a
    # duplicate recovery publish on an all-zero history flags as NOISE
    # (zero baseline has no relative move, but it still leaves OK)
    hist = [(f"r{i:02d}", dict(record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(record)
    worse["chaos_availability"] = 0.5
    worse["chaos_churn_p99_ms"] = 30000.0
    worse["chaos_recovery_dup_publishes"] = 3
    rep = evaluate_history(hist[:-1] + [("r99", worse)])
    flagged = [f.metric for f in rep.regressed()]
    assert "chaos_availability" in flagged
    assert "chaos_churn_p99_ms" in flagged
    dup = [f for f in rep.findings
           if f.metric == "chaos_recovery_dup_publishes"]
    assert dup and dup[0].severity > OK
    # the ISSUE 16 journal vocabulary is exported
    for ev in ("FLEET_CHAOS_INJECT", "FLEET_HEDGE_ISSUED",
               "FLEET_HEDGE_WON", "WORKER_JOIN", "WORKER_LEAVE",
               "LEASE_BACKEND_FAULT"):
        assert ev in EVENT_TYPES, ev
