"""Facade: the notebook's driver flow (construct -> get_economy_data ->
make_Mrkv_history -> solve -> read results) against the reference's interface
contract (SURVEY.md §1 L5->L4; Aiyagari-HARK.py:234-291)."""

import numpy as np
import pytest

from aiyagari_hark_tpu import (
    AggregateSavingRule,
    AiyagariEconomy,
    AiyagariType,
    init_aiyagari_agents,
    init_aiyagari_economy,
)

SMALL = dict(LaborStatesNo=5, act_T=300, T_discard=60, verbose=False)


@pytest.fixture(scope="module")
def solved():
    econ_dict = init_aiyagari_economy()
    econ_dict.update(SMALL, LaborAR=0.3, CRRA=1.0)
    agent_dict = init_aiyagari_agents()
    agent_dict.update(LaborStatesNo=5, AgentCount=100, aCount=16)
    economy = AiyagariEconomy(tolerance=0.02, **econ_dict)
    economy.verbose = False
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    economy.solve()
    return economy, agent


def test_quantile_resample_half_agent_tail_rule():
    """The equal-weight resample's top-agent pin (round-3 advisor fix,
    corrected in round 4): a ~1e-12 truncation-tail bucket at the top of
    the support must NOT capture an agent (1% of the panel standing on
    1e-12 of the mass dragged the unweighted mean 14% off the weighted
    mean), while a top bin holding at least half an agent's share (0.5/n)
    must still pin max(aNow) to the true support max."""
    from aiyagari_hark_tpu.facade import quantile_resample

    grid = np.linspace(0.0, 100.0, 201)          # support 0..100
    # lognormal-ish bulk around 5, hard-truncated: top bin gets 1e-12
    weights = np.exp(-0.5 * ((grid - 5.0) / 2.0) ** 2)
    weights[-1] = 1e-12
    weights /= weights.sum()
    panel = quantile_resample(grid, weights, 100)
    w_mean = float(np.average(grid, weights=weights))
    assert panel.max() < 20.0                     # no teleport to a_max
    assert abs(panel.mean() - w_mean) < 0.02 * abs(w_mean)
    assert np.all(np.diff(panel) >= 0)            # quantiles are ordered

    # material top-bin mass (>= 0.5/n): the support max IS the honest max
    weights2 = weights.copy()
    weights2[-1] = 0.01                           # 1% >> 0.5/100
    weights2 /= weights2.sum()
    panel2 = quantile_resample(grid, weights2, 100)
    assert panel2.max() == grid[-1]

    # adversarial half-mass gap (round-4 review): a 1e-12 bucket far above
    # the bulk must not drag ANY high quantile into the empty gap — the
    # trailing-tail trim protects agents 76..99, not just the pinned last
    g3 = np.array([0.0, 1.0, 2.0])
    w3 = np.array([0.5, 0.5 - 1e-12, 1e-12])
    panel3 = quantile_resample(g3, w3, 100)
    assert panel3.max() == 1.0                    # trimmed support max
    assert np.all(np.diff(panel3) >= 0)           # monotone panel
    assert np.all(panel3 <= 1.0)                  # nobody in the gap


def test_steady_state_attributes():
    economy = AiyagariEconomy(**init_aiyagari_economy())
    # closed forms from Aiyagari_Support.py:1606-1615 with beta=.96 a=.36 d=.08
    assert economy.KtoLSS == pytest.approx(
        ((1 / 0.96 - 0.92) / 0.36) ** (1 / (0.36 - 1.0)))
    assert economy.RSS == pytest.approx(
        1 + 0.36 * economy.KtoLSS ** (0.36 - 1) - 0.08)
    assert economy.MSS == pytest.approx(
        economy.KSS * economy.RSS + economy.WSS * 1.0)
    assert economy.sow_init["Mnow"] == pytest.approx(economy.MSS)


def test_mrkv_history_shape_and_seed():
    economy = AiyagariEconomy(**{**init_aiyagari_economy(), "act_T": 500})
    h1 = economy.make_Mrkv_history()
    h2 = economy.make_Mrkv_history()
    assert h1.shape == (500,)
    np.testing.assert_array_equal(h1, h2)   # seeded -> reproducible
    assert set(np.unique(h1)) <= {0, 1}


def test_solve_populates_reference_surface(solved):
    economy, agent = solved
    # sow_state / reap_state (Aiyagari-HARK.py:257-258)
    r_pct = (economy.sow_state["Rnow"] - 1) * 100
    assert 0.0 < r_pct < 15.0
    a_mean = np.mean(economy.reap_state["aNow"])
    d = economy.parameters["DeprFac"]
    saving = d * a_mean / (economy.sow_state["Mnow"] - (1 - d) * a_mean)
    assert 0.05 < saving < 0.6
    # track history
    assert economy.history["Mnow"].shape == (300,)
    assert np.all(np.isfinite(economy.history["Aprev"]))
    # AFunc callables (Aiyagari-HARK.py:286-287)
    x = np.linspace(0.1, 2 * economy.KSS, 50)
    y0 = economy.AFunc[0](x)
    assert y0.shape == x.shape and np.all(y0 > 0)
    # solution cFunc surface (Aiyagari-HARK.py:275)
    cf = agent.solution[0].cFunc
    assert len(cf) == 4 * 5
    c = cf[0](np.linspace(0.1, 10, 7), economy.MSS)
    assert c.shape == (7,) and np.all(np.diff(c) > 0)   # monotone in m
    xi = cf[0].xInterpolators
    assert len(xi) == len(agent.MgridBase)
    assert np.all(xi[3](np.linspace(0.1, 10, 7)) > 0)


def test_consumption_below_resources(solved):
    economy, agent = solved
    m = np.linspace(0.5, 20, 40)
    for s in (0, 9, 19):
        c = agent.solution[0].cFunc[s](m, economy.MSS)
        assert np.all(c <= m + 1e-6)
        assert np.all(c > 0)


def test_solve_requires_agents():
    economy = AiyagariEconomy(**init_aiyagari_economy())
    with pytest.raises(ValueError):
        economy.solve()


def test_aggregate_saving_rule_distance():
    a = AggregateSavingRule(0.1, 1.0)
    b = AggregateSavingRule(0.3, 0.9)
    assert a.distance(b) == pytest.approx(0.2)
    assert a(np.e) == pytest.approx(np.exp(0.1 + 1.0))


def test_repeat_solve_warm_starts(solved):
    """Solving twice continues from the converged rule (the reference's
    in-place intercept_prev/slope_prev mutation, quirk SURVEY.md §3.6-7,
    made explicit) — so the second solve converges in one iteration."""
    economy, agent = solved
    assert len(economy.solution.records) > 1
    economy.solve()
    assert len(economy.solution.records) == 1


def test_cfunc_accepts_array_M(solved):
    economy, agent = solved
    m = np.linspace(0.5, 10, 8)
    Ms = np.full(8, economy.MSS)
    paired = agent.solution[0].cFunc[0](m, Ms)
    scalar = agent.solution[0].cFunc[0](m, economy.MSS)
    np.testing.assert_allclose(paired, scalar, rtol=1e-6)


@pytest.mark.slow
def test_agent_level_crra_discfac_honored():
    """CRRA/DiscFac set only on AiyagariType must reach the solver instead of
    the economy default (VERDICT r1 weak-item 5)."""
    economy = AiyagariEconomy(tolerance=0.02,
                              **{**SMALL, "LaborAR": 0.3})
    economy.verbose = False
    agent = AiyagariType(LaborStatesNo=5, AgentCount=100, aCount=16,
                         CRRA=3.0, DiscFac=0.94)
    cfg = economy._economy_config_for(agent)
    assert cfg.crra == 3.0
    assert cfg.disc_fac == 0.94
    # and the agent-side config agrees
    acfg = agent.agent_config()
    assert acfg.crra == 3.0 and acfg.disc_fac == 0.94


def test_agent_economy_conflict_raises():
    economy = AiyagariEconomy(CRRA=1.0, verbose=False)
    agent = AiyagariType(CRRA=5.0)
    with pytest.raises(ValueError, match="CRRA"):
        economy._economy_config_for(agent)


@pytest.mark.slow
def test_solve_distribution_method_through_facade():
    """sim_method='distribution' flows through the facade: the result
    surface carries the wealth histogram as (support, weights) and the
    equilibrium sits at the deterministic (bisection-consistent) r*."""
    from fixture_configs import SOLVE_KWARGS, facade_distribution_updates
    fk = dict(SOLVE_KWARGS["facade_dist"])   # single source with the registry
    econ_dict = init_aiyagari_economy()
    econ_dict.update(facade_distribution_updates())   # + committed warm start
    agent_dict = init_aiyagari_agents()
    agent_dict.update(LaborStatesNo=5, AgentCount=fk.pop("AgentCount"),
                      aCount=fk.pop("aCount"))
    economy = AiyagariEconomy(tolerance=fk.pop("tolerance"), **econ_dict)
    economy.verbose = False
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    sol = economy.solve(**fk)
    assert sol.converged
    support = economy.reap_state["aNowGrid"][0]
    weights = economy.reap_state["aNowWeights"][0]
    assert support.shape == weights.shape
    np.testing.assert_allclose(weights.sum(), 1.0, atol=1e-8)
    # weighted mean of the histogram == the history's final aggregate
    mean_a = float(np.average(support, weights=weights))
    np.testing.assert_allclose(mean_a, float(sol.history.A_prev[-1]),
                               rtol=1e-6)
    # "aNow" is notebook-compatible in distribution mode too: an
    # equal-weight quantile resample whose UNWEIGHTED mean/std agree with
    # the exact weighted statistics (VERDICT r2 weak-item 6)
    panel = economy.reap_state["aNow"][0]
    assert panel.shape == (100,)          # AgentCount
    assert abs(float(np.mean(panel)) - mean_a) < 0.05 * abs(mean_a)
    wstd = float(np.sqrt(np.average((support - mean_a) ** 2,
                                    weights=weights)))
    assert abs(float(np.std(panel)) - wstd) < 0.1 * max(wstd, 1e-9)
    # pinned rule: slope 0 on the populated saving-rule surface
    assert economy.AFunc[0].slope == 0.0
