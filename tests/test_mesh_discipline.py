"""Mesh-discipline lint (ISSUE 20 satellite): hot paths build meshes and
shardings through the ``parallel.mesh`` seam, never raw
``Mesh``/``NamedSharding``/``PartitionSpec`` construction."""

import importlib.util
import os

import pytest

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "check_mesh_discipline",
    os.path.join(repo, "scripts", "check_mesh_discipline.py"))
lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(lint)


def test_mesh_discipline_lint_is_clean():
    findings = lint.scan()
    assert not findings, "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_mesh_discipline_covers_the_hot_dirs():
    rels = {os.path.relpath(p, repo).replace(os.sep, "/")
            for p in lint.scan_targets()}
    # the seam's consumers are in scope — including ops/, which consumes
    # shardings through constrain_state but must never mint geometry ...
    assert "aiyagari_hark_tpu/parallel/sweep.py" in rels
    assert "aiyagari_hark_tpu/parallel/panel.py" in rels
    assert "aiyagari_hark_tpu/ops/markov.py" in rels
    assert "aiyagari_hark_tpu/models/household.py" in rels
    assert any(r.startswith("aiyagari_hark_tpu/serve/") for r in rels)
    # ... and the seam file itself is walked but exempt from findings
    assert "aiyagari_hark_tpu/parallel/mesh.py" in rels
    assert not lint.scan_source(
        "from jax.sharding import Mesh\nm = Mesh((), ())\n",
        "aiyagari_hark_tpu/parallel/mesh.py")


@pytest.mark.parametrize("src,n_expected", [
    # a bare construction is a finding
    ("from jax.sharding import Mesh\n"
     "m = Mesh(devs, ('cells',))\n", 2),
    # attribute-form construction too
    ("import jax\n"
     "s = jax.sharding.NamedSharding(m, spec)\n", 1),
    # PartitionSpec minting is a finding
    ("from jax.sharding import PartitionSpec\n"
     "p = PartitionSpec('state', None)\n", 2),
    # a waived line is not
    ("from jax.sharding import Mesh  # mesh-ok: fixture\n"
     "m = Mesh(devs, ('cells',))  # mesh-ok: fixture\n", 0),
    # seam calls are never banned
    ("from ..parallel.mesh import state_mesh, state_sharding\n"
     "m = state_mesh(4)\n"
     "s = state_sharding(m, 'distribution')\n", 0),
])
def test_mesh_discipline_fixtures(src, n_expected):
    findings = lint.scan_source(src, "aiyagari_hark_tpu/models/x.py")
    assert len(findings) == n_expected, findings


def test_mesh_discipline_script_exit_codes():
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "check_mesh_discipline.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout
