"""Parallel layer on the 8-device virtual CPU mesh: sharded Table II sweep
equals the single-device sweep; sharded panel reproduces the aggregate
history of the unsharded panel."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_model import (
    AFuncParams,
    build_ks_calibration,
    solve_ks_household,
)
from aiyagari_hark_tpu.models.simulate import (
    initial_panel,
    simulate_markov_history,
    simulate_panel,
)
from aiyagari_hark_tpu.parallel import (
    initial_panel_sharded,
    make_mesh,
    run_table2_sweep,
    simulate_panel_sharded,
)
from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig, SweepConfig

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


SMALL_SWEEP = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
SMALL_KW = dict(a_count=16, dist_count=64, labor_states=5)


def test_mesh_construction():
    mesh = make_mesh(("cells", "agents"), (4, 2))
    assert mesh.shape == {"cells": 4, "agents": 2}
    mesh1 = make_mesh(("cells",))
    assert mesh1.shape == {"cells": 8}
    mesh2 = make_mesh(("a", "b"), (-1, 2))
    assert mesh2.shape == {"a": 4, "b": 2}


def test_sharded_sweep_matches_single_device():
    res1 = run_table2_sweep(SMALL_SWEEP, mesh=None, **SMALL_KW)
    mesh = make_mesh(("cells",))
    res8 = run_table2_sweep(SMALL_SWEEP, mesh=mesh, **SMALL_KW)
    np.testing.assert_allclose(res8.r_star_pct, res1.r_star_pct, atol=1e-9)
    np.testing.assert_allclose(res8.saving_rate_pct, res1.saving_rate_pct,
                               atol=1e-9)
    # economically sane: r* below the discount rate bound 1/beta-1 = 4.1666%
    assert (res1.r_star_pct < 100.0 * (1.0 / 0.96 - 1.0)).all()
    assert (res1.r_star_pct > 0.0).all()
    # higher risk aversion -> more precautionary saving -> lower r*
    r = {(s, rho): v for s, rho, v in
         zip(res1.crra, res1.labor_ar, res1.r_star_pct)}
    assert r[(3.0, 0.6)] < r[(1.0, 0.6)]
    assert np.isfinite(res1.wall_seconds) and res1.wall_seconds > 0
    assert "rho\\sigma" in res1.table()


def test_sharded_sweep_with_pallas_grid_matches_single_device():
    """The multi-chip scaling path's actual composition (VERDICT r4
    weak-item 2): the custom_vmap lane-grid Pallas dispatch
    (``household._pallas_fixed_point_vmappable``) under a
    ``NamedSharding``-sharded ``cells`` axis.  Every other mesh test lets
    ``dist_method`` resolve to scatter on CPU, so GSPMD partitioning
    around the (interpret-mode) Pallas call had zero coverage — and a
    Mosaic-grid kernel under a sharded batch axis is exactly the kind of
    composition that breaks (cf. the round-3 nested-vmap grid-rank bug).
    4 cells over 8 devices also exercises the edge-replication padding."""
    res1 = run_table2_sweep(SMALL_SWEEP, mesh=None, dist_method="pallas",
                            **SMALL_KW)
    mesh = make_mesh(("cells",))
    res8 = run_table2_sweep(SMALL_SWEEP, mesh=mesh, dist_method="pallas",
                            **SMALL_KW)
    assert res8.dist_method == "pallas"
    np.testing.assert_allclose(res8.r_star_pct, res1.r_star_pct, atol=1e-9)
    np.testing.assert_allclose(res8.capital, res1.capital, atol=1e-9)
    # and the kernel path agrees with the scatter path it replaces
    res_sc = run_table2_sweep(SMALL_SWEEP, mesh=mesh, dist_method="auto",
                              **SMALL_KW)
    np.testing.assert_allclose(res8.r_star_pct, res_sc.r_star_pct,
                               atol=1e-6)


def test_both_panels_batch_into_one_sweep():
    """labor_sd as a tuple adds the Table II panel axis: the sd=0.2 half
    of the 2-panel batch must equal the single-panel sweep cell for
    cell, and panel B (sd=0.4) must show lower r* (more income risk,
    more precautionary saving)."""
    both = run_table2_sweep(SweepConfig(crra_values=(1.0, 3.0),
                                        rho_values=(0.3, 0.6),
                                        labor_sd=(0.2, 0.4)), **SMALL_KW)
    assert both.r_star_pct.shape == (8,)
    one = run_table2_sweep(SMALL_SWEEP, **SMALL_KW)
    a_half = both.labor_sd == 0.2
    np.testing.assert_allclose(both.r_star_pct[a_half], one.r_star_pct,
                               atol=1e-9)
    assert (both.r_star_pct[~a_half] < both.r_star_pct[a_half]).all()
    assert "panel sd=0.4" in both.table()


def test_sweep_pads_odd_cell_counts():
    sweep = SweepConfig(crra_values=(1.0, 3.0, 5.0), rho_values=(0.3,))
    mesh = make_mesh(("cells",), (2,), devices=jax.devices()[:2])
    res = run_table2_sweep(sweep, mesh=mesh, **SMALL_KW)
    assert res.r_star_pct.shape == (3,)


def test_sweep_records_inner_loop_work():
    """Per-cell EGM/distribution iteration counters and the vmap-of-while
    skew diagnostic (VERDICT r1 #9)."""
    res = run_table2_sweep(SMALL_SWEEP, **SMALL_KW)
    assert (res.egm_iters > 0).all() and (res.dist_iters > 0).all()
    assert (res.total_work() == res.egm_iters + res.dist_iters).all()
    assert res.iteration_skew() >= 1.0
    # bisection runs tens of midpoints, each solving to a fixed point: the
    # totals must dominate the bisect count
    assert (res.egm_iters > res.bisect_iters).all()


def test_sweep_rejects_unhashable_kwargs():
    with pytest.raises(TypeError, match="not hashable"):
        run_table2_sweep(SMALL_SWEEP, bad_kwarg={"a": 1}, **SMALL_KW)


@pytest.fixture(scope="module")
def ks_setup():
    agent = AgentConfig(agent_count=64, a_count=16, labor_states=4)
    econ = EconomyConfig(labor_states=4, act_T=40, t_discard=10, verbose=False)
    cal = build_ks_calibration(agent, econ)
    afunc = AFuncParams(intercept=jnp.zeros(2), slope=jnp.ones(2))
    policy, _, _, _ = solve_ks_household(afunc, cal, tol=1e-5)
    key = jax.random.PRNGKey(3)
    mrkv = simulate_markov_history(cal.agg_transition, 0, econ.act_T,
                                   jax.random.PRNGKey(7))
    return agent, econ, cal, policy, mrkv, key


def test_sharded_panel_runs_and_aggregates(ks_setup):
    agent, econ, cal, policy, mrkv, key = ks_setup
    mesh = make_mesh(("agents",))
    init = initial_panel_sharded(cal, agent.agent_count, 0,
                                 jax.random.PRNGKey(1), mesh)
    assert init.assets.shape == (agent.agent_count,)
    hist, final = simulate_panel_sharded(policy, cal, mrkv, init, key, mesh)
    assert hist.A_prev.shape == (econ.act_T,)
    assert bool(jnp.all(jnp.isfinite(hist.A_prev)))
    assert bool(jnp.all(hist.A_prev > 0))
    assert final.assets.shape == (agent.agent_count,)
    # the sharded history must be economically close to an unsharded run of
    # the same size (different RNG stream -> statistical, not exact, match)
    init1 = initial_panel(cal, agent.agent_count, 0, jax.random.PRNGKey(1))
    hist1, _ = simulate_panel(policy, cal, mrkv, init1, key)
    ratio = float(jnp.mean(hist.A_prev) / jnp.mean(hist1.A_prev))
    assert 0.8 < ratio < 1.25


def test_sharded_panel_rejects_indivisible_agents(ks_setup):
    agent, econ, cal, policy, mrkv, key = ks_setup
    mesh = make_mesh(("agents",))
    with pytest.raises(ValueError):
        initial_panel_sharded(cal, 63, 0, jax.random.PRNGKey(1), mesh)


def test_multihost_single_process_noop(monkeypatch):
    """multihost.initialize() is a clean no-op without a coordinator (the
    single-host path every script takes by default), and the coordinator
    guard reports this process as process 0 of 1."""
    from aiyagari_hark_tpu.parallel import multihost

    for var in ("JAX_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    assert multihost.initialize() is False
    assert multihost.is_coordinator()
    assert multihost.process_count() == 1


def test_multihost_refuses_silent_duplicate_jobs(monkeypatch):
    """num_processes > 1 without a coordinator must raise — N independent
    duplicate single-process jobs would otherwise run silently."""
    import pytest

    from aiyagari_hark_tpu.parallel import multihost

    for var in ("JAX_COORDINATOR_ADDRESS", "TPU_WORKER_HOSTNAMES",
                "MEGASCALE_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(var, raising=False)
    with pytest.raises(ValueError, match="duplicate"):
        multihost.initialize(num_processes=4, process_id=0)
