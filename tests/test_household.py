"""Household-solver tests: Euler-equation residuals, budget identities,
monotonicity, and stationary-distribution invariants (SURVEY.md §4 test
pyramid: kernel-level checks against theory the reference never had)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.solver_health import CONVERGED
from aiyagari_hark_tpu.models.household import (
    aggregate_capital,
    aggregate_labor,
    build_simple_model,
    consumption_at,
    solve_household,
    stationary_wealth,
    wealth_transition,
    _push_forward,
)
from aiyagari_hark_tpu.models import firm

DISC, CRRA, ALPHA, DELTA = 0.96, 1.0, 0.36, 0.08


@pytest.fixture(scope="module")
def model():
    return build_simple_model(labor_states=7, labor_ar=0.3, labor_sd=0.2,
                              dist_count=300)


@pytest.fixture(scope="module")
def prices():
    # prices at a plausible r below the discount rate
    r = 0.038
    k_to_l = firm.k_to_l_from_r(r, ALPHA, DELTA)
    return 1.0 + r, float(firm.wage_rate(k_to_l, ALPHA))


@pytest.fixture(scope="module")
def solved(model, prices):
    R, W = prices
    policy, iters, diff, status = solve_household(R, W, model, DISC, CRRA)
    return policy, int(iters), float(diff), int(status)


def test_egm_converges(solved):
    _, iters, diff, status = solved
    assert diff < 1e-6
    assert iters < 3000
    assert status == CONVERGED


def test_euler_equation_residual(model, prices, solved):
    """Off the borrowing constraint, u'(c(m)) = beta R E[u'(c(R a' + W l'))]."""
    R, W = prices
    policy, _, _, _ = solved
    n = model.labor_levels.shape[0]
    m = jnp.linspace(2.0, 30.0, 50)
    max_rel = 0.0
    for s in range(n):
        c = consumption_at(policy, m, s)
        a_next = m - c
        interior = np.asarray(a_next) > 0.05
        m_next = R * a_next[:, None] + W * model.labor_levels[None, :]
        c_next = jax.vmap(lambda mm: consumption_at(policy, mm))(m_next)
        rhs = DISC * R * (c_next ** (-CRRA) @ model.transition[s])
        lhs = c ** (-CRRA)
        rel = np.abs(np.asarray(lhs - rhs)) / np.asarray(lhs)
        if interior.any():
            max_rel = max(max_rel, float(rel[interior].max()))
    # linear-interp discretization error dominates; residual must be small
    assert max_rel < 5e-3, max_rel


def test_policy_monotone_and_budget(model, prices, solved):
    R, W = prices
    policy, _, _, _ = solved
    m = jnp.linspace(0.5, 40.0, 200)
    for s in (0, 3, 6):
        c = np.asarray(consumption_at(policy, m, s))
        assert np.all(np.diff(c) > 0), "consumption increasing in m"
        a_next = np.asarray(m) - c
        assert np.all(np.diff(a_next) >= -1e-10), "savings nondecreasing in m"
        assert np.all(c > 0)
        assert np.all(a_next > -1e-7), "borrowing constraint respected"


def test_constrained_region_consumes_everything(model, prices, solved):
    """Below the first endogenous knot the agent consumes ~all resources
    (the reference's prepended (1e-7, 1e-7) constraint segment)."""
    R, W = prices
    policy, _, _, _ = solved
    m0 = float(policy.m_knots[0, 1])  # first endogenous knot, poorest state
    m = jnp.asarray(0.5 * m0)
    c = float(consumption_at(policy, m, 0))
    assert abs(c - float(m)) / float(m) < 2e-3


def test_stationary_distribution_invariants(model, prices, solved):
    R, W = prices
    policy, _, _, _ = solved
    dist, iters, diff, status = stationary_wealth(policy, R, W, model)
    assert int(status) == CONVERGED
    d = np.asarray(dist)
    assert abs(d.sum() - 1.0) < 1e-8
    assert (d >= -1e-15).all()
    # labor marginal matches the stationary labor distribution
    np.testing.assert_allclose(d.sum(axis=0), np.asarray(model.labor_stationary),
                               atol=1e-6)
    # invariance under one more push-forward
    trans = wealth_transition(policy, R, W, model)
    d2 = _push_forward(dist, trans, model.transition)
    np.testing.assert_allclose(np.asarray(d2), d, atol=1e-9)
    # aggregate capital is positive and finite
    K = float(aggregate_capital(dist, model))
    assert 0.1 < K < 50.0


def test_aggregate_labor_near_one(model):
    # normalized levels have unweighted mean 1; stationary mean is close
    L = float(aggregate_labor(model))
    assert 0.85 < L < 1.1


def test_impatience_supply_rises_with_r(model):
    """Capital supply is increasing in r near equilibrium (bisection validity)."""
    supplies = []
    for r in (0.02, 0.041):
        k_to_l = firm.k_to_l_from_r(r, ALPHA, DELTA)
        W = float(firm.wage_rate(k_to_l, ALPHA))
        policy, _, _, _ = solve_household(1.0 + r, W, model, DISC, CRRA)
        dist, _, _, _ = stationary_wealth(policy, 1.0 + r, W, model)
        supplies.append(float(aggregate_capital(dist, model)))
    assert supplies[1] > supplies[0]


@pytest.mark.slow
def test_stationary_methods_agree(model, prices, solved):
    """The three distribution-iteration backends — scatter (CPU), dense
    operator (MXU matvecs), and the Pallas VMEM-resident kernel (interpret
    mode here) — are the same linear operator, so their fixed points must
    agree to solver tolerance."""
    R, W = prices
    policy, _, _, _ = solved
    ref, _, _, _ = stationary_wealth(policy, R, W, model, method="scatter")
    for method in ("dense", "pallas"):
        d, it, diff, _ = stationary_wealth(policy, R, W, model, method=method)
        np.testing.assert_allclose(np.asarray(d), np.asarray(ref),
                                   atol=1e-9, err_msg=method)
        assert int(it) > 0 and float(diff) <= 1e-11
    # the direct LU solve targets the same fixed point but certifies via a
    # plain-step residual rather than iterating to 1e-11
    d, it, diff, _ = stationary_wealth(policy, R, W, model, method="solve")
    np.testing.assert_allclose(np.asarray(d), np.asarray(ref),
                               atol=1e-8, err_msg="solve")
    assert float(diff) < 1e-9
    with pytest.raises(ValueError):
        stationary_wealth(policy, R, W, model, method="bogus")


def test_dense_operator_is_push_forward(model, prices, solved):
    """One dense step == one scatter step exactly (same linear operator)."""
    from aiyagari_hark_tpu.models.household import (
        _push_forward_dense,
        dense_wealth_operator,
        initial_distribution,
    )

    R, W = prices
    policy, _, _, _ = solved
    trans = wealth_transition(policy, R, W, model)
    S = dense_wealth_operator(trans, model.dist_grid.shape[0])
    # columns of each S[n] are lotteries: they sum to 1 exactly
    np.testing.assert_allclose(np.asarray(S.sum(axis=1)), 1.0, atol=1e-12)
    d0 = initial_distribution(model)
    one_scatter = _push_forward(d0, trans, model.transition)
    one_dense = _push_forward_dense(d0, S, model.transition)
    np.testing.assert_allclose(np.asarray(one_dense),
                               np.asarray(one_scatter), atol=1e-12)


@pytest.mark.slow
def test_pallas_kernel_under_vmap():
    """The sweep vmaps the whole cell solve; the Pallas fixed-point kernel
    must survive that transformation (interpret mode on CPU)."""
    from aiyagari_hark_tpu.models.household import (
        dense_wealth_operator,
        initial_distribution,
        solve_household,
        wealth_transition,
    )
    from aiyagari_hark_tpu.ops.pallas_kernels import stationary_dense_pallas

    m = build_simple_model(labor_states=3, a_count=12, dist_count=40)
    d0 = initial_distribution(m)

    def solve_at(r):
        k_to_l = firm.k_to_l_from_r(r, ALPHA, DELTA)
        W = firm.wage_rate(k_to_l, ALPHA)
        pol, _, _, _ = solve_household(1.0 + r, W, m, DISC, CRRA)
        trans = wealth_transition(pol, 1.0 + r, W, m)
        S = dense_wealth_operator(trans, m.dist_grid.shape[0])
        dist, _, _ = stationary_dense_pallas(S, m.transition, d0, 1e-10,
                                             interpret=True)
        return aggregate_capital(dist, m)

    rs = jnp.array([0.02, 0.035])
    batched = jax.vmap(solve_at)(rs)
    serial = jnp.stack([solve_at(rs[0]), solve_at(rs[1])])
    np.testing.assert_allclose(np.asarray(batched), np.asarray(serial),
                               rtol=1e-8)


@pytest.mark.slow
def test_pallas_lane_grid_dispatch_under_vmap():
    """``stationary_wealth(method='pallas')`` under vmap must reroute
    through the custom_vmap batching rule to the LANE-GRID kernel (one
    program instance per lane — the round-3 change that lets the Table II
    sweep use Pallas at all) and agree with the serial scatter oracle."""
    from aiyagari_hark_tpu.models.household import stationary_wealth

    m = build_simple_model(labor_states=3, a_count=12, dist_count=40)

    def dist_at(r, method):
        k_to_l = firm.k_to_l_from_r(r, ALPHA, DELTA)
        W = firm.wage_rate(k_to_l, ALPHA)
        pol, _, _, _ = solve_household(1.0 + r, W, m, DISC, CRRA)
        d, _, _, _ = stationary_wealth(pol, 1.0 + r, W, m, tol=1e-10,
                                    method=method)
        return d

    rs = jnp.array([0.02, 0.03, 0.035])
    batched = jax.vmap(lambda r: dist_at(r, "pallas"))(rs)
    serial = jnp.stack([dist_at(r, "scatter") for r in rs])
    np.testing.assert_allclose(np.asarray(batched), np.asarray(serial),
                               atol=1e-8)


@pytest.mark.skipif(
    __import__("jax").default_backend() not in ("tpu", "axon"),
    reason="compiled Mosaic kernel requires a TPU backend (tests run on the "
           "virtual CPU mesh; bench attempt 2 pins dist_method='scatter' as "
           "the production hedge)")
def test_pallas_kernel_compiled_on_tpu(model, prices, solved):
    """interpret=False coverage: the Mosaic-lowered kernel must agree with
    the scatter fixed point when a real TPU is attached."""
    from aiyagari_hark_tpu.models.household import (
        dense_wealth_operator,
        initial_distribution,
    )
    from aiyagari_hark_tpu.ops.pallas_kernels import stationary_dense_pallas

    R, W = prices
    policy, _, _, _ = solved
    ref, _, _, _ = stationary_wealth(policy, R, W, model, method="scatter")
    trans = wealth_transition(policy, R, W, model)
    S = dense_wealth_operator(trans, model.dist_grid.shape[0])
    d, _, _ = stationary_dense_pallas(S, model.transition,
                                      initial_distribution(model), 1e-11,
                                      interpret=False)
    np.testing.assert_allclose(np.asarray(d), np.asarray(ref), atol=1e-8)


def test_pallas_nested_vmap_collapses_to_lane_grid():
    """A doubly-vmapped 'pallas' fixed point (heterogeneity's beta-dist
    sweep over cells) must degrade gracefully: the grid dispatch's own
    batching rule collapses the extra axis into the lane axis instead of
    vmap-batching the pallas_call itself (round-3 review)."""
    from aiyagari_hark_tpu.models import firm

    m = build_simple_model(labor_states=5, a_count=24, dist_count=60)

    def one(r, beta, method):
        W = firm.wage_rate(firm.k_to_l_from_r(r, 0.36, 0.08), 0.36)
        pol, _, _, _ = solve_household(1.0 + r, W, m, beta, 2.0)
        d, _, _, _ = stationary_wealth(pol, 1.0 + r, W, m, method=method)
        return d

    rs = jnp.asarray([0.02, 0.03])
    betas = jnp.asarray([0.95, 0.97])
    dp = jax.vmap(lambda b: jax.vmap(lambda r: one(r, b, "pallas"))(rs))(
        betas)
    dd = jax.vmap(lambda b: jax.vmap(lambda r: one(r, b, "dense"))(rs))(
        betas)
    assert dp.shape == dd.shape == (2, 2, 60, 5)
    np.testing.assert_allclose(np.asarray(dp), np.asarray(dd), atol=1e-12)


def test_pallas_egm_single_lane_matches_xla(model, prices):
    """The EGM policy fixed point as a Pallas kernel (ISSUE 2 tentpole):
    interpret mode runs the IDENTICAL iteration code, so the unbatched
    kernel must take the same iteration path (same step count, same
    status) and land on the XLA while_loop's fixed point to float-fusion
    noise (XLA may fuse the step's ops differently inside vs outside the
    interpreted kernel — bit-equality is not part of the contract)."""
    R, W = prices
    px, itx, dx, sx = solve_household(R, W, model, DISC, CRRA, tol=1e-7)
    pp, itp, dp, sp = solve_household(R, W, model, DISC, CRRA, tol=1e-7,
                                      method="pallas")
    np.testing.assert_allclose(np.asarray(px.m_knots),
                               np.asarray(pp.m_knots), rtol=1e-12)
    np.testing.assert_allclose(np.asarray(px.c_knots),
                               np.asarray(pp.c_knots), rtol=1e-12)
    assert int(itx) == int(itp) and int(sx) == int(sp) == CONVERGED
    with pytest.raises(ValueError, match="method"):
        solve_household(R, W, model, DISC, CRRA, method="bogus")


def test_pallas_egm_grid_dispatch_under_vmap(model, prices):
    """A vmapped 'pallas' EGM solve must reroute to the lane-GRID kernel
    (custom_vmap), each lane exiting at its own convergence: per-lane
    results equal the UNBATCHED solves exactly (the grid runs each lane's
    program alone), and the lock-step vmap(xla) path to float tolerance
    (batched matmul contraction rounds differently)."""
    R, W = prices
    crras = jnp.asarray([1.0, 2.0, 5.0])

    def solve(crra, method):
        pol, it, _, status = solve_household(R, W, model, DISC, crra,
                                             tol=1e-7, method=method)
        return pol.c_knots, it, status

    c_g, it_g, s_g = jax.vmap(lambda c: solve(c, "pallas"))(crras)
    c_x, it_x, s_x = jax.vmap(lambda c: solve(c, "xla"))(crras)
    assert np.asarray(s_g).tolist() == np.asarray(s_x).tolist()
    # per-lane exit: iteration counts are lane-local, not the batch max
    assert np.array_equal(np.asarray(it_g), np.asarray(it_x))
    np.testing.assert_allclose(np.asarray(c_g), np.asarray(c_x), atol=1e-10)
    for i, crra in enumerate([1.0, 2.0, 5.0]):
        c1, _, _ = solve(jnp.asarray(crra), "xla")
        np.testing.assert_allclose(np.asarray(c1), np.asarray(c_g)[i],
                                   rtol=1e-12)


def test_pallas_egm_inside_lean_equilibrium(model):
    """egm_method threads through the bisection equilibrium: the lean
    solve with the kernel engine lands on the XLA engine's r* (identical
    iteration code; trajectories match to solver noise)."""
    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    kw = dict(labor_states=4, a_count=12, dist_count=48, r_tol=1e-5,
              max_bisect=25)
    lean_x = solve_calibration_lean(2.0, 0.3, egm_method="xla", **kw)
    lean_p = solve_calibration_lean(2.0, 0.3, egm_method="pallas", **kw)
    assert abs(float(lean_x.r_star) - float(lean_p.r_star)) < 1e-6
    assert int(lean_p.status) == CONVERGED
