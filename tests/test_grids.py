"""Grid construction + GridPolicy resolution (ISSUE 12, DESIGN §5b).

The contracts under test:

* ``make_grid_exp_mult`` endpoint fidelity and strict monotonicity on
  BOTH branches (nested ``timestonest > 0`` and log-linear
  ``timestonest == 0``), and the typed ``ValueError`` on a lower
  endpoint outside the branch's log domain (``ming <= 0`` log-linear,
  ``ming <= -1`` nested) — previously a silent NaN/-inf grid.
* ``resolve_grid`` mirrors ``resolve_precision``: known policies
  resolve, unknown ones raise before they can alias a cache key, and
  ``hashable_kwargs`` drops the explicit default (the no-drift pin)
  while keeping non-default policies distinct.
* ``build_asset_grids``: the "reference" path is bit-identical to the
  raw builders; compact grids are strictly monotone TRUNCATIONS of the
  reference grids (kept points bit-equal, knee honored, support span
  preserved) with fewer points.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.ops.grids import (
    GRID_POLICIES,
    build_asset_grids,
    compact_knee,
    grid_point_counts,
    make_asset_grid,
    make_grid_exp_mult,
    resolve_grid,
)
from aiyagari_hark_tpu.utils.fingerprint import (
    hashable_kwargs,
    work_fingerprint,
)


# -- make_grid_exp_mult: endpoint fidelity, monotonicity, typed domain ------

@pytest.mark.parametrize("nest", [0, 1, 2, 3])
def test_exp_mult_endpoints_and_monotone(nest):
    g = np.asarray(make_grid_exp_mult(0.001, 50.0, 32, nest))
    assert g.shape == (32,)
    np.testing.assert_allclose(g[0], 0.001, rtol=0, atol=1e-12)
    np.testing.assert_allclose(g[-1], 50.0, rtol=1e-12)
    assert (np.diff(g) > 0).all()
    assert np.isfinite(g).all()


def test_exp_mult_log_linear_branch_rejects_nonpositive_min():
    # timestonest=0 takes log(ming): ming <= 0 used to produce NaN/-inf
    # gridpoints silently (ISSUE 12 satellite) — now a typed ValueError
    with pytest.raises(ValueError, match="timestonest=0"):
        make_grid_exp_mult(0.0, 50.0, 16, 0)
    with pytest.raises(ValueError, match="timestonest=0"):
        make_grid_exp_mult(-0.5, 50.0, 16, 0)


def test_exp_mult_nested_branch_rejects_min_at_or_below_minus_one():
    # the nested branch takes log(1 + ming): the domain edge is -1
    with pytest.raises(ValueError, match="ming > -1"):
        make_grid_exp_mult(-1.0, 50.0, 16, 2)
    # a negative ming above -1 is legal there (shifted Huggett grids)
    g = np.asarray(make_grid_exp_mult(-0.5, 50.0, 16, 2))
    assert np.isfinite(g).all() and (np.diff(g) > 0).all()


def test_exp_mult_rejects_degenerate_spans_and_counts():
    with pytest.raises(ValueError, match="two grid points"):
        make_grid_exp_mult(0.001, 50.0, 1, 2)
    with pytest.raises(ValueError, match="ordered"):
        make_grid_exp_mult(50.0, 0.001, 16, 2)


# -- GridPolicy resolution ---------------------------------------------------

def test_resolve_grid_policies():
    assert resolve_grid("reference").compact is False
    assert resolve_grid("reference").ladder is False
    for name in ("compact", "adaptive"):
        spec = resolve_grid(name)
        assert spec.compact and spec.ladder
        assert spec.coarse_tol_factor >= 1.0
    assert set(GRID_POLICIES) == {"reference", "compact", "adaptive"}
    # a spec passes through (the bench's tuning path)
    spec = resolve_grid("compact")
    assert resolve_grid(spec) is spec


def test_resolve_grid_unknown_raises():
    with pytest.raises(ValueError, match="grid policy"):
        resolve_grid("sparse")
    with pytest.raises(ValueError, match="grid policy"):
        resolve_grid(None)


def test_hashable_kwargs_grid_no_drift_pin():
    # explicit default dropped: the two spellings share every
    # fingerprint and executable cache entry
    assert hashable_kwargs({"grid": "reference", "a_count": 10}) \
        == hashable_kwargs({"a_count": 10})
    # non-default policies are distinct from the default AND each other
    ref = work_fingerprint(hashable_kwargs({"a_count": 10}), np.float64)
    cmp_ = work_fingerprint(
        hashable_kwargs({"grid": "compact", "a_count": 10}), np.float64)
    ada = work_fingerprint(
        hashable_kwargs({"grid": "adaptive", "a_count": 10}), np.float64)
    assert len({ref, cmp_, ada}) == 3
    # unknown policies fail at normalization, not deep in a cache
    with pytest.raises(ValueError, match="grid policy"):
        hashable_kwargs({"grid": "bogus"})


# -- build_asset_grids: the resolution seam ---------------------------------

def test_reference_grids_bit_identical_to_raw_builders():
    a_grid, dist_grid, knee = build_asset_grids(
        "reference", 0.001, 50.0, 24, 2, 150)
    assert knee is None
    raw_a = make_asset_grid(0.001, 50.0, 24, 2)
    raw_inner = make_grid_exp_mult(0.001, 50.0, 149, 2)
    assert np.asarray(a_grid).tobytes() == np.asarray(raw_a).tobytes()
    expect = np.concatenate([[0.0], np.asarray(raw_inner)])
    assert np.asarray(dist_grid).tobytes() == expect.tobytes()


@pytest.mark.parametrize("policy", ["compact", "adaptive"])
@pytest.mark.parametrize("tail", ["analytic", "anchors"])
def test_compact_grids_are_monotone_truncations(policy, tail):
    ref_a, ref_d, _ = build_asset_grids("reference", 0.001, 50.0, 24, 2,
                                        150)
    a_grid, dist_grid, knee = build_asset_grids(
        policy, 0.001, 50.0, 24, 2, 150, tail=tail)
    a, d = np.asarray(a_grid), np.asarray(dist_grid)
    assert knee is not None and 0.001 < knee < 50.0
    assert (np.diff(a) > 0).all() and (np.diff(d) > 0).all()
    # every kept point is a BIT-equal member of the reference grid
    # (truncation, not re-spacing — the curved region's discretization
    # is the goldens' own)
    ref_a_set = set(np.asarray(ref_a).tolist())
    ref_d_set = set(np.asarray(ref_d).tolist())
    assert all(x in ref_a_set for x in a.tolist())
    assert all(x in ref_d_set for x in d.tolist())
    # fewer points (the analytic variant drops the whole solver tail;
    # anchors can only thin what exists — at a small a_count the tail
    # may already be at the anchor floor), histogram span preserved
    if tail == "analytic":
        assert len(a) < len(np.asarray(ref_a))
    else:
        assert len(a) <= len(np.asarray(ref_a))
    assert len(d) < len(np.asarray(ref_d))
    assert d[-1] == np.asarray(ref_d)[-1]
    assert d[0] == 0.0
    if tail == "analytic":
        # the solver grid is the curved region only: it stops at the knee
        assert a[-1] <= knee
    else:
        # anchors close the span structurally
        assert a[-1] == np.asarray(ref_a)[-1]


def test_compact_point_counts_match_built_grids():
    for policy in ("compact", "adaptive"):
        a_grid, dist_grid, _ = build_asset_grids(
            policy, 0.001, 50.0, 24, 2, 150)
        na, nd = grid_point_counts(policy, 24, 150)
        assert na == np.asarray(a_grid).shape[0]
        assert nd == np.asarray(dist_grid).shape[0]
    assert grid_point_counts("reference", 24, 150) == (24, 150)
    # the compaction saves real points on the golden config (the raw
    # point saving is modest by design — the drift budget pins the
    # curved region to reference density; the step-work saving is the
    # bench's grid_effective_reduction)
    na, nd = grid_point_counts("compact", 24, 150)
    assert na + nd < 0.95 * (24 + 150)
    na5, nd5 = grid_point_counts("compact", 100, 500)
    assert na5 + nd5 < 0.92 * (100 + 500)


def test_adaptive_knee_sits_below_compact_knee():
    # adaptive's lower density quantile truncates more aggressively
    k_cmp = compact_knee(resolve_grid("compact"), 0.001, 50.0, 24, 2)
    k_ada = compact_knee(resolve_grid("adaptive"), 0.001, 50.0, 24, 2)
    assert k_ada < k_cmp


def test_borrow_limit_shifts_compact_grids():
    a_grid, dist_grid, _ = build_asset_grids(
        "compact", 0.001, 50.0, 24, 2, 150, borrow_limit=-2.0)
    assert float(np.asarray(dist_grid)[0]) == -2.0
    assert float(np.asarray(a_grid)[0]) == pytest.approx(-2.0 + 0.001)
    d = np.asarray(dist_grid)
    assert (np.diff(d) > 0).all()
    # top of the support = b + span = -2 + (50 - (-2)) = 50
    assert d[-1] == pytest.approx(50.0)


def test_build_asset_grids_rejects_unknown_tail():
    with pytest.raises(ValueError, match="tail"):
        build_asset_grids("compact", 0.001, 50.0, 24, 2, 150,
                          tail="linear")


def test_compact_dtype_cast():
    a32, d32, _ = build_asset_grids("compact", 0.001, 50.0, 24, 2, 150,
                                    dtype=jnp.float32)
    assert a32.dtype == jnp.float32 and d32.dtype == jnp.float32
