"""Bench-regression sentinel (ISSUE 10, ``obs.regress`` +
``scripts/check_bench_regress.py``).

Pins the acceptance contract:

* the sentinel is CLEAN on the committed BENCH_r*.json history (no
  REGRESSED finding — the tier-1 gate ``check_bench_regress.main``
  exits 0);
* a 20% injected synthetic slowdown MUST flag REGRESSED — both on a
  stable synthetic history and on a stable metric of the committed
  history — and journals a typed REGRESSION_FLAGGED event under an
  active obs scope;
* severities are ordered OK < NOISE < REGRESSED and the band rules
  (median-of-last-K baseline, IQR noise band, worse-than-worst-prior
  gate, 10% actionability line) grade deterministically;
* the direction-of-goodness table is COMPLETE over every numeric field
  of every committed bench record (strict resolution never raises).
"""

import copy
import json
import os
import sys

import pytest

from aiyagari_hark_tpu.obs import ObsConfig, build_obs, read_journal
from aiyagari_hark_tpu.obs.regress import (
    DOWN,
    NEUTRAL,
    NOISE,
    OK,
    REGRESSED,
    UP,
    UnknownMetricError,
    direction_of_goodness,
    evaluate_history,
    flatten_record,
    grade_metric,
    load_bench_history,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_bench_regress  # noqa: E402


def _committed():
    history = load_bench_history(REPO)
    assert len(history) >= 2, "committed BENCH history went missing"
    return history


# ---------------------------------------------------------------------------
# Clean on committed history.
# ---------------------------------------------------------------------------

def test_committed_history_is_clean():
    report = evaluate_history(_committed())
    assert report.worst < REGRESSED, report.summary()
    assert report.regressed() == []
    # and nothing rode along ungraded
    assert report.unknown_fields == []


def test_check_script_exits_clean_on_committed_history(capsys):
    assert check_bench_regress.main([]) == 0
    out = capsys.readouterr().out
    assert "bench-regress" in out and "REGRESSED" not in out.split("\n")[0]


def test_check_script_json_mode(capsys):
    assert check_bench_regress.main(["--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["worst"] < REGRESSED
    assert payload["findings"]


# ---------------------------------------------------------------------------
# Injected slowdowns must flag.
# ---------------------------------------------------------------------------

def test_injected_20pct_slowdown_on_committed_history_flags():
    history = copy.deepcopy(_committed())
    # iteration_skew is stable across committed rounds — the 20%
    # synthetic slowdown drill of the ISSUE 10 acceptance
    history[-1][1]["iteration_skew"] *= 1.2
    report = evaluate_history(history)
    assert report.worst == REGRESSED
    assert [f.metric for f in report.regressed()] == ["iteration_skew"]
    finding = report.regressed()[0]
    assert finding.delta_frac == pytest.approx(0.2, abs=0.05)
    assert finding.direction == DOWN


def test_injected_slowdown_on_synthetic_stable_history_flags():
    synth = [(f"r{i:02d}", {"value": v})
             for i, v in enumerate([10.0, 10.1, 9.9, 10.05])]
    synth.append(("r99", {"value": 12.0}))        # +20% wall
    report = evaluate_history(synth)
    assert report.worst == REGRESSED
    assert report.regressed()[0].metric == "value"


def test_improvement_and_noise_grades():
    # an IMPROVEMENT (wall down) never flags
    synth = [(f"r{i}", {"value": v}) for i, v in
             enumerate([10.0, 10.1, 9.9, 8.0])]
    assert evaluate_history(synth).worst == OK
    # outside the band but under the 10% actionability line -> NOISE
    history = copy.deepcopy(_committed())
    history[-1][1]["iteration_skew"] *= 1.06
    report = evaluate_history(history)
    assert report.worst == NOISE
    assert [f.metric for f in report.noisy()] == ["iteration_skew"]


def test_regression_flagged_event_journaled(tmp_path):
    jp = str(tmp_path / "events.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    history = copy.deepcopy(_committed())
    history[-1][1]["iteration_skew"] *= 1.3
    with obs.activate():
        evaluate_history(history)
    obs.close()
    events = read_journal(jp, event="REGRESSION_FLAGGED")
    assert len(events) == 1
    assert events[0]["metric"] == "iteration_skew"
    assert events[0]["direction"] == DOWN


# ---------------------------------------------------------------------------
# Grading rules.
# ---------------------------------------------------------------------------

def test_severity_order_is_total():
    assert OK < NOISE < REGRESSED


def test_grade_metric_rules():
    priors = [10.0, 10.2, 9.9, 10.1]
    # inside the band: OK
    assert grade_metric("x_wall_s", 10.3, priors).severity == OK
    # beyond band but NOT beyond the worst prior: OK (history already
    # contained a worse committed value)
    assert grade_metric("x_wall_s", 10.9,
                        priors + [11.5]).severity == OK
    # beyond both, >= 10% -> REGRESSED
    f = grade_metric("x_wall_s", 12.0, priors)
    assert f.severity == REGRESSED and f.worst_prior == 10.2
    # beyond both, < 10% -> NOISE
    assert grade_metric("x_wall_s", 10.8, priors).severity == NOISE
    # an UP metric regresses downward
    assert grade_metric("x_per_sec", 8.0, priors).severity == REGRESSED
    # neutral metrics never grade
    assert grade_metric("n_devices", 99.0, priors).severity == OK
    # insufficient history is OK-with-a-note, never a guess
    f = grade_metric("x_wall_s", 99.0, [10.0])
    assert f.severity == OK and "insufficient history" in f.note


# ---------------------------------------------------------------------------
# Direction-of-goodness completeness.
# ---------------------------------------------------------------------------

def test_direction_table_complete_for_every_committed_numeric_field():
    seen = 0
    for _, record in _committed():
        for field in flatten_record(record):
            direction = direction_of_goodness(field, strict=True)
            assert direction in (UP, DOWN, NEUTRAL)
            seen += 1
    assert seen > 20    # the committed history really was traversed


def test_direction_known_fields_and_nesting():
    assert direction_of_goodness("value") == DOWN
    assert direction_of_goodness("vs_baseline") == UP
    assert direction_of_goodness("mfu_pct") == UP
    assert direction_of_goodness("last_tpu.compile_s") == DOWN
    assert direction_of_goodness("egm_gridpoints_per_sec_per_chip") == UP
    assert direction_of_goodness("r_star_f32_f64_max_bp") == DOWN
    assert direction_of_goodness("profile_overhead_frac") == DOWN


def test_direction_covers_chips_scaling_record():
    """The ``--chips-scaling`` leg's scalar fields (ISSUE 11) resolve
    strictly — the sentinel grades a chips record from its FIRST
    committed round instead of raising unclassified — and a synthetic
    chips history grades clean end to end."""
    chips_record = {
        "metric": "chips_scaling", "backend": "cpu",
        "chips_forced_host": True, "chips_smoke_cells": 24,
        "chips_scaling": [{"n_devices": 1, "wall_s": 2.0,
                           "cells_per_sec": 12.0}],
        "chips_bit_identical": True,
        "chips_device_work_skew": 1.1,
        "chips_mem_stats_devices": 0,
        "chips_mem_peak_bytes": None,
        "chips_cells_per_sec_1dev": 12.0,
        "chips_cells_per_sec_2dev": 22.0,
        "chips_cells_per_sec_4dev": 40.0,
        "chips_cells_per_sec_8dev": 72.0,
        "chips_speedup_2dev": 1.8, "chips_speedup_4dev": 3.3,
        "chips_speedup_8dev": 6.0, "chips_speedup_ok": True,
    }
    for field in flatten_record(chips_record):
        direction = direction_of_goodness(field, strict=True)
        assert direction in (UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("chips_cells_per_sec_8dev") == UP
    assert direction_of_goodness("chips_speedup_8dev") == UP
    assert direction_of_goodness("chips_device_work_skew") == DOWN
    # a stable synthetic chips history grades clean; a throughput drop
    # at 8 devices flags REGRESSED in the declared (UP) direction
    hist = [(f"r{i:02d}", dict(chips_record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(chips_record)
    worse["chips_cells_per_sec_8dev"] = 40.0
    hist_bad = hist[:-1] + [("r99", worse)]
    flagged = [f.metric for f in evaluate_history(hist_bad).regressed()]
    assert "chips_cells_per_sec_8dev" in flagged


def test_direction_covers_compaction_smoke_record():
    """The ``--compaction-smoke`` leg's scalar fields (ISSUE 12) resolve
    strictly — the sentinel grades the grid_* record from its FIRST
    committed round — and a synthetic grid history grades clean, with a
    gridpoint increase / certified-count drop flagging in the declared
    directions (gridpoints down = good, certified up = good)."""
    grid_record = {
        "metric": "compaction_smoke", "backend": "cpu",
        "grid_cells": 12, "grid_knee": 19.2,
        "grid_points_reference": 174, "grid_points_compact": 150,
        "grid_point_reduction": 1.16,
        "grid_total_inner_steps_reference": 256733,
        "grid_total_inner_steps_compact": 221000,
        "grid_step_reduction": 1.16,
        "grid_effective_gridpoint_steps_reference": 2050000,
        "grid_effective_gridpoint_steps_compact": 1020000,
        "grid_effective_reduction": 2.0,
        "grid_reference_wall_s": 104.3, "grid_compact_wall_s": 82.0,
        "grid_wall_reduction": 1.27,
        "grid_cert_levels": [0] * 12,
        "grid_cells_certified": 12, "grid_all_certified": True,
        "grid_r_drift_max_bp": 0.05, "grid_drift_under_budget": True,
        "grid_escalations": 0,
        "grid_reference_bit_identical": True,
    }
    for field in flatten_record(grid_record):
        direction = direction_of_goodness(field, strict=True)
        assert direction in (UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("grid_points_compact") == DOWN
    assert direction_of_goodness("grid_cells_certified") == UP
    assert direction_of_goodness("grid_effective_reduction") == UP
    assert direction_of_goodness("grid_r_drift_max_bp") == DOWN
    assert direction_of_goodness("grid_compact_wall_s") == DOWN
    # stable synthetic history grades clean; a gridpoint blow-up and a
    # certified-count drop both flag in the declared directions
    hist = [(f"r{i:02d}", dict(grid_record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(grid_record)
    worse["grid_points_compact"] = 174
    worse["grid_cells_certified"] = 9
    hist_bad = hist[:-1] + [("r99", worse)]
    flagged = [f.metric for f in evaluate_history(hist_bad).regressed()]
    assert "grid_points_compact" in flagged
    assert "grid_cells_certified" in flagged


def test_direction_covers_kernel_smoke_record():
    """The ``--kernel-smoke`` leg's scalar fields (ISSUE 13) resolve
    strictly — the sentinel grades the kernel_* record from its FIRST
    committed round — and a synthetic kernel history grades clean, with
    a fused-wall blow-up / certified-count drop / throughput collapse
    flagging in the declared directions."""
    kernel_record = {
        "metric": "kernel_smoke", "backend": "cpu",
        "kernel_cells": 12,
        "kernel_reference_wall_s": 95.0, "kernel_fused_wall_s": 90.0,
        "kernel_wall_reduction": 1.06,
        "kernel_reference_egm_gridpoints_per_sec_per_chip": 170000.0,
        "kernel_fused_egm_gridpoints_per_sec_per_chip": 180000.0,
        "kernel_cert_levels": [0] * 12,
        "kernel_cells_certified": 12, "kernel_all_certified": True,
        "kernel_r_drift_max_bp": 0.01, "kernel_drift_under_budget": True,
        "kernel_escalations": 0,
        "kernel_reference_bit_identical": True,
        "kernel_drill_escalations": 1,
        "kernel_drill_max_knot_diff": 2e-6,
        "kernel_drill_recovered": True,
        "kernel_fused_executables": 3, "kernel_fused_launches": 14,
        "kernel_fused_mfu_pct": 0.4,
        "kernel_roofline": "memory", "kernel_roofline_not_latency": True,
        "kernel_sentinel_clean": True, "kernel_sentinel_worst": "OK",
    }
    for field in flatten_record(kernel_record):
        direction = direction_of_goodness(field, strict=True)
        assert direction in (UP, DOWN, NEUTRAL), field
    assert direction_of_goodness(
        "kernel_fused_egm_gridpoints_per_sec_per_chip") == UP
    assert direction_of_goodness("kernel_fused_wall_s") == DOWN
    assert direction_of_goodness("kernel_wall_reduction") == UP
    assert direction_of_goodness("kernel_cells_certified") == UP
    assert direction_of_goodness("kernel_r_drift_max_bp") == DOWN
    assert direction_of_goodness("kernel_escalations") == DOWN
    # stable synthetic history grades clean; a fused-wall blow-up and a
    # certified-count drop both flag in the declared directions
    hist = [(f"r{i:02d}", dict(kernel_record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(kernel_record)
    worse["kernel_fused_wall_s"] = 140.0
    worse["kernel_cells_certified"] = 9
    hist_bad = hist[:-1] + [("r99", worse)]
    flagged = [f.metric for f in evaluate_history(hist_bad).regressed()]
    assert "kernel_fused_wall_s" in flagged
    assert "kernel_cells_certified" in flagged


def test_direction_unknown_field_raises_strict_only():
    with pytest.raises(UnknownMetricError):
        direction_of_goodness("utterly_unclassifiable_thing",
                              strict=True)
    assert direction_of_goodness("utterly_unclassifiable_thing",
                                 strict=False) == NEUTRAL


def test_flatten_record_skips_non_scalars():
    flat = flatten_record({"a": 1, "b": True, "c": "x", "d": [1, 2],
                           "e": {"f": 2.5}, "g": None})
    assert flat == {"a": 1.0, "e.f": 2.5}


def test_direction_covers_surrogate_smoke_record():
    """The ``--surrogate-smoke`` leg's scalar fields (ISSUE 17) resolve
    strictly — the sentinel grades the surrogate/index record from its
    FIRST committed round — and a synthetic history grades clean, with
    a hit-rate drop / index slowdown flagging in the declared (UP)
    directions."""
    surrogate_record = {
        "metric": "surrogate_smoke", "backend": "cpu",
        "index_speedup_1e4": 4.3, "index_grid_ms_1e4": 0.22,
        "index_linear_ms_1e4": 0.93, "index_bitwise_ok_1e4": True,
        "index_speedup_5e4": 14.0, "index_grid_ms_5e4": 0.39,
        "index_linear_ms_5e4": 5.52, "index_bitwise_ok_5e4": True,
        "index_entries": 50_000, "index_rebuilds": 2,
        "surrogate_hit_rate": 0.5152, "surrogate_escalation_rate": 0.15,
        "surrogate_escalations": 3, "surrogate_audits": 2,
        "surrogate_audit_failures": 0, "surrogate_refinements": 3,
        "surrogate_bound_p50": 0.004, "surrogate_bound_p95": 0.02,
        "surrogate_p50_ms": 0.4, "surrogate_p95_ms": 0.9,
        "surrogate_queries": 21, "surrogate_served": 17,
        "surrogate_sub_ms": True, "surrogate_bound_max": 0.05,
        "surrogate_tagged": True, "surrogate_never_cached": True,
        "surrogate_escalated_certified": True,
        "surrogate_audits_within_bound": True,
        "surrogate_refined_published": 3,
        "surrogate_events_served": 17, "surrogate_events_escalated": 3,
        "surrogate_index_kind": "grid",
        "surrogate_warm_wall_s": 60.0,
        "surrogate_sentinel_clean": True,
        "surrogate_sentinel_worst": "OK",
    }
    for field in flatten_record(surrogate_record):
        direction = direction_of_goodness(field, strict=True)
        assert direction in (UP, DOWN, NEUTRAL), field
    assert direction_of_goodness("surrogate_hit_rate") == UP
    assert direction_of_goodness("surrogate_escalation_rate") == DOWN
    assert direction_of_goodness("surrogate_audit_failures") == DOWN
    assert direction_of_goodness("surrogate_bound_p95") == DOWN
    assert direction_of_goodness("index_speedup_5e4") == UP
    assert direction_of_goodness("index_grid_ms_5e4") == DOWN
    assert direction_of_goodness("index_linear_ms_5e4") == NEUTRAL
    # a stable synthetic history grades clean; a hit-rate collapse and
    # an index slowdown both flag REGRESSED in the declared directions
    hist = [(f"r{i:02d}", dict(surrogate_record)) for i in range(4)]
    assert evaluate_history(hist).worst == OK
    worse = dict(surrogate_record)
    worse["surrogate_hit_rate"] = 0.1
    worse["index_speedup_5e4"] = 1.2
    flagged = [f.metric
               for f in evaluate_history(hist[:-1]
                                         + [("r99", worse)]).regressed()]
    assert "surrogate_hit_rate" in flagged
    assert "index_speedup_5e4" in flagged
