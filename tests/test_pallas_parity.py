"""Interpret-mode parity for EVERY Pallas kernel (ISSUE 13 satellite).

Historically the compiled kernels were exercised only when a TPU
answered the probe — kernel logic had zero CI coverage.  These tests
run each kernel under ``interpret=True`` on CPU against its XLA twin on
a small lane, so a logic regression in a kernel body fails tier-1
without hardware.  The kernels share the exact iteration code with the
XLA paths (``accelerated_*_fixed_point``), so parity is tight: step
counts match EXACTLY; values agree to float-fusion noise (the fused
kernel's tiled push-forward contraction reorders reductions — the
documented tolerance is 1e-9 relative / 1e-8 absolute in f64, the
~tol/(1-lambda) slow-mode bound both engines' certified update norms
imply).

The fused megakernel additionally gets the 12-golden-cell parity pin
(the ISSUE 13 acceptance): every (sigma, rho) Table II cell's supply
evaluation, fused-vs-reference, within the documented tolerance —
vmapped, so it rides the custom_vmap lane-grid dispatch exactly like
the sweep does.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import aiyagari_hark_tpu.models.household as hh
from aiyagari_hark_tpu.models.equilibrium import household_capital_supply
from aiyagari_hark_tpu.models.household import (
    HouseholdPolicy,
    accelerated_distribution_fixed_point,
    accelerated_policy_fixed_point,
    build_simple_model,
    dense_wealth_operator,
    egm_step,
    initial_distribution,
    initial_policy,
    solve_household,
    wealth_transition,
)
from aiyagari_hark_tpu.ops.pallas_kernels import (
    _PROBES,
    egm_policy_pallas,
    egm_policy_pallas_grid,
    fused_cell_pallas,
    fused_cell_pallas_grid,
    probe_kernel,
    stationary_dense_pallas,
    stationary_dense_pallas_grid,
)

TOL_KW = dict(rtol=1e-9, atol=1e-8)   # the documented parity tolerance


@pytest.fixture(scope="module")
def model():
    return build_simple_model(labor_states=3, a_count=12, dist_count=48)


@pytest.fixture(scope="module")
def solved(model):
    pol, _, _, _ = solve_household(1.02, 1.0, model, 0.96, 2.0)
    return pol


def _scalars(model, R=1.02, W=1.0, disc=0.96, crra=2.0):
    dt = model.a_grid.dtype
    return jnp.asarray([R, W, disc, crra,
                        float(model.borrow_limit)], dtype=dt)


# -- probe registry (the dedupe satellite) ----------------------------------

def test_probe_registry_covers_every_kernel_and_validates():
    assert {"dense", "dense_grid", "egm", "egm_grid",
            "fused", "fused_grid"} == set(_PROBES)
    with pytest.raises(ValueError, match="unknown kernel probe"):
        probe_kernel("warp")
    # off-TPU every probe is False (and memoized, not an error)
    for name in _PROBES:
        assert probe_kernel(name) is False


def test_legacy_probe_spellings_alias_the_registry():
    from aiyagari_hark_tpu.ops.pallas_kernels import (
        pallas_egm_grid_tpu_available,
        pallas_egm_tpu_available,
        pallas_grid_tpu_available,
        pallas_tpu_available,
    )

    assert pallas_tpu_available() is probe_kernel("dense")
    assert pallas_grid_tpu_available() is probe_kernel("dense_grid")
    assert pallas_egm_tpu_available() is probe_kernel("egm")
    assert pallas_egm_grid_tpu_available() is probe_kernel("egm_grid")


# -- per-kernel interpret parity --------------------------------------------

def test_dense_kernel_interpret_parity(model, solved):
    trans = wealth_transition(solved, 1.02, 1.0, model)
    S = dense_wealth_operator(trans, model.dist_grid.shape[0])
    d0 = initial_distribution(model)
    ref_d, ref_it, ref_diff, _ = accelerated_distribution_fixed_point(
        lambda d: hh._push_forward_dense(d, S, model.transition),
        d0, 1e-10, 5000, 64)
    ker_d, ker_it, ker_diff = stationary_dense_pallas(
        S, model.transition, d0, 1e-10, 5000, 64, interpret=True)
    assert int(ker_it) == int(ref_it)
    np.testing.assert_allclose(np.asarray(ker_d), np.asarray(ref_d),
                               **TOL_KW)


def test_dense_grid_kernel_interpret_parity(model, solved):
    trans = wealth_transition(solved, 1.02, 1.0, model)
    S1 = dense_wealth_operator(trans, model.dist_grid.shape[0])
    d0 = initial_distribution(model)
    S = jnp.stack([S1, 0.5 * (S1 + jnp.transpose(S1, (0, 2, 1)))])
    P = jnp.stack([model.transition, model.transition])
    d0s = jnp.stack([d0, d0])
    dg, itg, diffg = stationary_dense_pallas_grid(
        S, P, d0s, 1e-10, 5000, 64, interpret=True)
    for i in range(2):
        d1, it1, _ = stationary_dense_pallas(
            S[i], P[i], d0s[i], 1e-10, 5000, 64, interpret=True)
        assert int(itg[i]) == int(it1)
        np.testing.assert_allclose(np.asarray(dg[i]), np.asarray(d1),
                                   rtol=1e-12, atol=1e-15)


def test_egm_kernel_interpret_parity(model):
    p0 = initial_policy(model)
    ref_p, ref_it, _, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, 1.02, 1.0, model, 0.96, 2.0),
        p0, 1e-6, 3000, 32)
    m, c, it, diff = egm_policy_pallas(
        p0.m_knots, p0.c_knots, model.a_grid, model.labor_levels,
        model.transition, _scalars(model), 1e-6, 3000, 32,
        interpret=True)
    assert int(it) == int(ref_it)
    np.testing.assert_allclose(np.asarray(c), np.asarray(ref_p.c_knots),
                               **TOL_KW)
    np.testing.assert_allclose(np.asarray(m), np.asarray(ref_p.m_knots),
                               **TOL_KW)


def test_egm_grid_kernel_interpret_parity(model):
    p0 = initial_policy(model)
    n = model.labor_levels.shape[0]
    m0 = jnp.stack([p0.m_knots, p0.m_knots])
    c0 = jnp.stack([p0.c_knots, p0.c_knots])
    a = jnp.stack([model.a_grid, model.a_grid])
    lvl = jnp.stack([model.labor_levels, model.labor_levels])
    P = jnp.stack([model.transition, model.transition])
    scal = jnp.stack([_scalars(model), _scalars(model, crra=3.0)])
    mg, cg, itg, _ = egm_policy_pallas_grid(
        m0, c0, a, lvl, P, scal, 1e-6, 3000, 32, interpret=True)
    for i, crra in enumerate((2.0, 3.0)):
        m1, c1, it1, _ = egm_policy_pallas(
            p0.m_knots, p0.c_knots, model.a_grid, model.labor_levels,
            model.transition, _scalars(model, crra=crra), 1e-6, 3000, 32,
            interpret=True)
        assert int(itg[i]) == int(it1)
        np.testing.assert_allclose(np.asarray(cg[i]), np.asarray(c1),
                                   rtol=1e-12, atol=1e-15)


def test_fused_kernel_interpret_parity(model):
    """The megakernel vs the two XLA loops it fuses: identical step
    counts (same iteration code), values within the documented
    tolerance (the tiled contraction reorders the push-forward's
    reductions)."""
    p0 = initial_policy(model)
    d0 = initial_distribution(model)
    h = jnp.zeros_like(model.labor_levels)
    m, c, dist, egm_it, _, dist_it, _ = fused_cell_pallas(
        p0.m_knots, p0.c_knots, model.a_grid, model.dist_grid,
        model.labor_levels, model.transition, _scalars(model), h, d0,
        1e-6, 3000, 32, 1e-10, 5000, 64, interpret=True)
    ref_p, ref_eit, _, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, 1.02, 1.0, model, 0.96, 2.0),
        p0, 1e-6, 3000, 32)
    trans = wealth_transition(ref_p, 1.02, 1.0, model)
    S = dense_wealth_operator(trans, model.dist_grid.shape[0])
    ref_d, ref_dit, _, _ = accelerated_distribution_fixed_point(
        lambda d: hh._push_forward_dense(d, S, model.transition),
        d0, 1e-10, 5000, 64)
    assert int(egm_it) == int(ref_eit)
    assert int(dist_it) == int(ref_dit)
    np.testing.assert_allclose(np.asarray(c), np.asarray(ref_p.c_knots),
                               **TOL_KW)
    np.testing.assert_allclose(np.asarray(dist), np.asarray(ref_d),
                               **TOL_KW)


def test_fused_kernel_analytic_tail_parity(model):
    """``tail=True``: the in-kernel tail closure (precomputed human
    wealth, in-kernel MPC slope) == the XLA tail-closed iteration."""
    R, W, disc, crra = 1.02, 1.0, 0.96, 2.0
    p0 = initial_policy(model, analytic_tail=True)
    d0 = initial_distribution(model)
    h = hh.perfect_foresight_human_wealth(
        jnp.asarray(R, model.a_grid.dtype),
        jnp.asarray(W, model.a_grid.dtype),
        model.labor_levels, model.transition)
    m, c, dist, egm_it, _, _, _ = fused_cell_pallas(
        p0.m_knots, p0.c_knots, model.a_grid, model.dist_grid,
        model.labor_levels, model.transition, _scalars(model), h, d0,
        1e-6, 3000, 32, 1e-10, 5000, 64, tail=True, interpret=True)
    ref_p, ref_eit, _, _ = accelerated_policy_fixed_point(
        lambda p: egm_step(p, R, W, model, disc, crra,
                           analytic_tail=True),
        p0, 1e-6, 3000, 32)
    assert int(egm_it) == int(ref_eit)
    assert m.shape == ref_p.m_knots.shape      # [N, A+3] tail-closed
    np.testing.assert_allclose(np.asarray(c), np.asarray(ref_p.c_knots),
                               **TOL_KW)


def test_fused_grid_kernel_interpret_parity(model):
    p0 = initial_policy(model)
    d0 = initial_distribution(model)
    h = jnp.zeros_like(model.labor_levels)
    stack2 = lambda x: jnp.stack([x, x])   # noqa: E731
    scal = jnp.stack([_scalars(model), _scalars(model, R=1.03)])
    mg, cg, dg, eitg, _, ditg, _ = fused_cell_pallas_grid(
        stack2(p0.m_knots), stack2(p0.c_knots), stack2(model.a_grid),
        stack2(model.dist_grid), stack2(model.labor_levels),
        stack2(model.transition), scal, stack2(h), stack2(d0),
        1e-6, 3000, 32, 1e-10, 5000, 64, interpret=True)
    for i, R in enumerate((1.02, 1.03)):
        m1, c1, d1, eit1, _, dit1, _ = fused_cell_pallas(
            p0.m_knots, p0.c_knots, model.a_grid, model.dist_grid,
            model.labor_levels, model.transition,
            _scalars(model, R=R), h, d0,
            1e-6, 3000, 32, 1e-10, 5000, 64, interpret=True)
        assert int(eitg[i]) == int(eit1)
        assert int(ditg[i]) == int(dit1)
        np.testing.assert_allclose(np.asarray(cg[i]), np.asarray(c1),
                                   rtol=1e-12, atol=1e-15)
        np.testing.assert_allclose(np.asarray(dg[i]), np.asarray(d1),
                                   rtol=1e-12, atol=1e-15)


# -- the 12-golden-cell fused acceptance pin --------------------------------

GOLDEN_CELLS = [(s, r) for s in (1.0, 3.0, 5.0)
                for r in (0.0, 0.3, 0.6, 0.9)]


def test_fused_supply_parity_on_all_golden_cells():
    """ISSUE 13 acceptance: fused == XLA reference within the documented
    tolerance on every (sigma, rho) Table II cell — vmapped, so the 12
    lanes ride the custom_vmap lane-grid dispatch exactly like a sweep
    bucket does.  (Smoke grid sizes: the full-size leg is the bench's
    ``--kernel-smoke``.)"""
    kw = dict(labor_states=3, a_count=12, dist_count=48)
    sig = jnp.asarray([c[0] for c in GOLDEN_CELLS], dtype=jnp.float64)
    rho = jnp.asarray([c[1] for c in GOLDEN_CELLS], dtype=jnp.float64)

    def supply(crra, labor_ar, kernel):
        m = build_simple_model(labor_ar=labor_ar, **kw)
        ev = household_capital_supply(0.02, m, 0.96, crra, 0.36, 0.08,
                                      kernel=kernel)
        return ev.supply, ev.egm_iters, ev.dist_iters, ev.status

    s_ref, e_ref, d_ref, st_ref = jax.jit(jax.vmap(
        lambda s, r: supply(s, r, "reference")))(sig, rho)
    s_fus, e_fus, d_fus, st_fus = jax.jit(jax.vmap(
        lambda s, r: supply(s, r, "fused")))(sig, rho)
    np.testing.assert_array_equal(np.asarray(st_fus), np.asarray(st_ref))
    np.testing.assert_allclose(np.asarray(s_fus), np.asarray(s_ref),
                               rtol=1e-9)
    # same iteration code — the vmapped reference runs lock-step while
    # the fused lane grid exits per lane, but each LANE's own certified
    # step counts are engine-independent
    np.testing.assert_array_equal(np.asarray(e_fus), np.asarray(e_ref))
    np.testing.assert_array_equal(np.asarray(d_fus), np.asarray(d_ref))
