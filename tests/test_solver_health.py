"""Solver-health layer: typed status codes, in-loop NaN tripwires, and the
sweep quarantine/retry escalation — every path exercised by DETERMINISTIC
fault injection (``solver_health.inject_fault`` at the loop level, the
``inject_fault=`` hook of ``run_table2_sweep`` at the sweep level), so the
tripwires are tested without waiting for natural divergence.

The load-bearing claims:
  * a NaN iterate exits a fixed point immediately as NONFINITE — it must
    neither masquerade as convergence (``NaN > tol`` is False) nor burn
    the iteration budget;
  * MAX_ITER is distinguishable from CONVERGED;
  * the distribution loop's stall window reports STALLED;
  * one injected-NaN sweep cell is quarantined, retried, and recovered
    while every OTHER cell's Table II values stay bit-identical to an
    uninjected run;
  * a diverged facade solve raises ``SolverDivergenceError`` instead of
    returning silent garbage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
from aiyagari_hark_tpu.models.household import (
    accelerated_distribution_fixed_point,
    accelerated_policy_fixed_point,
    build_simple_model,
    egm_step,
    initial_policy,
)
from aiyagari_hark_tpu.solver_health import (
    CONVERGED,
    MAX_ITER,
    NONFINITE,
    STALLED,
    SolverDivergenceError,
    combine_status,
    inject_fault,
    is_failure,
    status_name,
)

BETA, CRRA = 0.96, 2.0
SMALL = dict(labor_states=5, a_count=16, dist_count=64)


@pytest.fixture(scope="module")
def model():
    return build_simple_model(**SMALL)


@pytest.fixture(scope="module")
def egm(model):
    return lambda p: egm_step(p, 1.02, 1.0, model, BETA, CRRA)


# -- the code algebra ------------------------------------------------------

def test_status_severity_and_combine():
    assert CONVERGED < STALLED < MAX_ITER < NONFINITE
    assert int(combine_status(CONVERGED, STALLED)) == STALLED
    assert int(combine_status(STALLED, MAX_ITER)) == MAX_ITER
    assert int(combine_status(NONFINITE, CONVERGED)) == NONFINITE
    # elementwise over per-cell arrays (the sweep's form)
    a = np.array([CONVERGED, MAX_ITER, STALLED])
    b = np.array([STALLED, CONVERGED, NONFINITE])
    np.testing.assert_array_equal(
        np.asarray(combine_status(a, b)), [STALLED, MAX_ITER, NONFINITE])


def test_is_failure_gate():
    assert not is_failure(CONVERGED) and not is_failure(STALLED)
    assert is_failure(MAX_ITER) and is_failure(NONFINITE)
    np.testing.assert_array_equal(
        is_failure(np.array([CONVERGED, STALLED, MAX_ITER, NONFINITE])),
        [False, False, True, True])


def test_status_names():
    assert [status_name(c) for c in range(4)] == [
        "CONVERGED", "STALLED", "MAX_ITER", "NONFINITE"]
    assert "UNKNOWN" in status_name(17)


def test_inject_fault_rejects_unknown_mode():
    with pytest.raises(ValueError, match="mode"):
        inject_fault(lambda x: x, mode="bogus")


# -- policy loop tripwires -------------------------------------------------

def test_policy_healthy_exit_is_converged(egm, model):
    pol, it, diff, status = accelerated_policy_fixed_point(
        egm, initial_policy(model), 1e-6, 3000)
    assert int(status) == CONVERGED
    assert float(diff) <= 1e-6 and int(it) < 3000


def test_policy_nan_fault_exits_nonfinite_immediately(egm, model):
    """A NaN at iteration 5 must exit within a step or two of 5 — not
    report CONVERGED (the NaN > tol masquerade) and not burn 3000 steps."""
    bad = inject_fault(egm, mode="nan", at_iter=5)
    _, it, diff, status = accelerated_policy_fixed_point(
        bad, initial_policy(model), 1e-6, 3000)
    assert int(status) == NONFINITE
    assert not np.isfinite(float(diff))
    assert int(it) <= 7, "tripwire must fire at the poisoned iterate"


def test_policy_stall_fault_exits_max_iter_not_converged(egm, model):
    """MAX_ITER != CONVERGED: an oscillating iterate above tol must burn
    the (small) budget and say so."""
    stall = inject_fault(egm, mode="stall", at_iter=0, amplitude=1e-3)
    _, it, diff, status = accelerated_policy_fixed_point(
        stall, initial_policy(model), 1e-6, 150)
    assert int(status) == MAX_ITER
    assert int(it) == 150
    assert float(diff) > 1e-6


# -- distribution loop tripwires (cheap synthetic contraction) -------------

def _affine_push(target, rate=0.5):
    """x -> target + rate * (x - target): a contraction with known fixed
    point — milliseconds per step, so the 512-step stall window is cheap."""
    return lambda x: target + rate * (x - target)


def test_distribution_healthy_exit_is_converged():
    target = jnp.linspace(0.0, 1.0, 32).reshape(8, 4)
    d0 = jnp.zeros((8, 4))
    dist, it, diff, status = accelerated_distribution_fixed_point(
        _affine_push(target), d0, 1e-10, 5000, accel_every=0)
    assert int(status) == CONVERGED
    np.testing.assert_allclose(np.asarray(dist), np.asarray(target),
                               atol=1e-8)


def test_distribution_nan_fault_exits_nonfinite_immediately():
    target = jnp.ones((8, 4))
    bad = inject_fault(_affine_push(target), mode="nan", at_iter=3)
    _, it, _, status = accelerated_distribution_fixed_point(
        bad, jnp.zeros((8, 4)), 1e-10, 5000, accel_every=0)
    assert int(status) == NONFINITE
    assert int(it) <= 5


def test_distribution_stall_fault_exits_stalled():
    """The alternating-offset fault pins the diff near 2*amplitude: the
    best certified residual stops improving and the 512-step stall window
    must exit STALLED (not burn max_iter, not claim convergence)."""
    target = jnp.ones((8, 4))
    stall = inject_fault(_affine_push(target), mode="stall", at_iter=0,
                         amplitude=1e-4)
    _, it, best, status = accelerated_distribution_fixed_point(
        stall, jnp.zeros((8, 4)), 1e-10, 20000, accel_every=0)
    assert int(status) == STALLED
    assert int(it) < 20000
    assert 1e-10 < float(best)


def test_distribution_max_iter_exit():
    target = jnp.ones((8, 4))
    _, it, _, status = accelerated_distribution_fixed_point(
        _affine_push(target, rate=0.999), jnp.zeros((8, 4)), 1e-14, 50,
        accel_every=0)
    assert int(status) == MAX_ITER
    assert int(it) == 50


# -- equilibrium bisection tripwires ---------------------------------------

def test_lean_equilibrium_healthy_status(model):
    lean = solve_calibration_lean(1.0, 0.3, labor_sd=0.2, **SMALL)
    assert int(lean.status) == CONVERGED
    assert not is_failure(int(lean.status))


def test_lean_equilibrium_nan_fault_trips_nonfinite(model):
    lean = solve_calibration_lean(1.0, 0.3, labor_sd=0.2, fault_iter=2,
                                  fault_mode="nan", **SMALL)
    assert int(lean.status) == NONFINITE
    # the tripwire exits on the poisoned evaluation, not at max_bisect
    assert int(lean.bisect_iters) == 3


def test_lean_equilibrium_stall_fault_trips_max_iter(model):
    lean = solve_calibration_lean(1.0, 0.3, labor_sd=0.2, fault_iter=1,
                                  fault_mode="stall", max_bisect=8, **SMALL)
    assert int(lean.status) == MAX_ITER
    assert int(lean.bisect_iters) == 8


# -- sweep quarantine/retry (the acceptance criterion) ---------------------

@pytest.mark.slow
def test_sweep_quarantines_retries_and_leaves_others_bit_identical():
    """ISSUE acceptance: a sweep with one deterministically fault-injected
    cell completes, quarantines/retries that cell, reports its status, and
    leaves all other cells' Table II values bit-identical to an uninjected
    run."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    base = run_table2_sweep(sweep, **SMALL)
    assert base.status is not None and base.retries is not None
    assert base.status.dtype == np.int64
    # satellite ADVICE r5 #2: counters are integers again on the host
    assert base.bisect_iters.dtype == np.int64
    assert base.egm_iters.dtype == np.int64
    assert base.dist_iters.dtype == np.int64
    assert not base.failed_cells().size
    assert (base.retries == 0).all()

    cell = 2
    inj = run_table2_sweep(
        sweep, inject_fault={"cell": cell, "at_iter": 1, "mode": "nan"},
        **SMALL)
    others = [i for i in range(4) if i != cell]
    # bit-identical, not allclose: the other lanes ran the same lock-step
    # masked program
    assert np.array_equal(base.r_star_pct[others], inj.r_star_pct[others])
    assert np.array_equal(base.capital[others], inj.capital[others])
    # the injected cell was quarantined, retried, and recovered
    assert inj.retries[cell] >= 1
    assert not is_failure(int(inj.status[cell]))
    assert np.isfinite(inj.r_star_pct[cell])
    assert abs(inj.r_star_pct[cell] - base.r_star_pct[cell]) < 1e-3


@pytest.mark.slow
def test_sweep_without_quarantine_reports_raw_failure():
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.config import SweepConfig

    sweep = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
    res = run_table2_sweep(
        sweep, inject_fault={"cell": 1, "at_iter": 0, "mode": "nan"},
        quarantine=False, **SMALL)
    assert int(res.status[1]) == NONFINITE
    assert int(res.retries[1]) == 0
    assert 1 in res.failed_cells()


# -- facade / KS outer loop ------------------------------------------------

def test_ks_divergence_raises_typed_error():
    """An aggregate state that never appears in the regression window
    makes the saving-rule OLS non-finite — the outer loop must raise the
    typed error with the status trail, not return garbage."""
    from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
    from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig

    agent = AgentConfig(labor_states=5, a_count=16, agent_count=40)
    econ = EconomyConfig(labor_states=5, act_T=60, t_discard=20,
                         max_loops=2, verbose=False)
    # a chain pinned to state 0: state 1's masked OLS sample is empty
    mrkv = np.zeros(60, dtype=np.int64)
    with pytest.raises(SolverDivergenceError) as ei:
        solve_ks_economy(agent, econ, mrkv_hist=mrkv)
    assert ei.value.status == NONFINITE
    assert ei.value.trail, "the error must carry the status trail"


def test_facade_solve_propagates_divergence_error():
    from aiyagari_hark_tpu import AiyagariEconomy, AiyagariType

    economy = AiyagariEconomy(LaborStatesNo=5, act_T=60, T_discard=20,
                              max_loops=2, verbose=False)
    agent = AiyagariType(LaborStatesNo=5, AgentCount=40, aCount=16)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.MrkvNow_hist = np.zeros(60, dtype=np.int64)
    with pytest.raises(SolverDivergenceError):
        economy.solve()
