"""Analytics: Lorenz shares, weighted percentiles, Gini, wealth stats —
against closed forms and degenerate cases."""

import numpy as np
import pytest

from aiyagari_hark_tpu.utils.stats import (
    get_lorenz_shares,
    get_percentiles,
    gini,
    histogram_sample,
    lorenz_distance,
    lorenz_distance_vs_scf,
    load_scf_lorenz,
    load_scf_wealth_weights,
    wealth_stats,
)


def test_lorenz_equal_wealth_is_diagonal():
    data = np.full(1000, 3.7)
    p = np.linspace(0.05, 0.95, 10)
    np.testing.assert_allclose(get_lorenz_shares(data, percentiles=p), p,
                               atol=1e-3)


def test_lorenz_concentrated_wealth():
    # one agent owns everything: Lorenz stays at 0 below the owner's rank
    # and reaches 1 at the top
    data = np.concatenate([np.zeros(999), [1000.0]])
    shares = get_lorenz_shares(data, percentiles=np.array([0.5, 0.999, 1.0]))
    assert shares[0] < 1e-6 and shares[1] < 1e-6
    assert shares[2] == pytest.approx(1.0)


def test_lorenz_weights_equivalent_to_replication():
    rng = np.random.default_rng(0)
    data = rng.lognormal(size=200)
    reps = rng.integers(1, 5, size=200)
    expanded = np.repeat(data, reps)
    p = np.linspace(0.1, 0.9, 9)
    np.testing.assert_allclose(
        get_lorenz_shares(data, weights=reps, percentiles=p),
        get_lorenz_shares(expanded, percentiles=p), atol=1e-9)


def test_percentiles_weighted():
    d = np.array([1.0, 2.0, 3.0, 4.0])
    # HARK get_percentiles semantics: interp on plain normalized cumulative
    # weights (cum=[.25,.5,.75,1.0] -> p=0.5 lands exactly on 2.0)
    assert get_percentiles(d, percentiles=(0.5,))[0] == pytest.approx(2.0)
    # weighting the top obs heavily pulls the median up
    w = np.array([1.0, 1.0, 1.0, 10.0])
    assert get_percentiles(d, weights=w, percentiles=(0.5,))[0] > 3.0


def test_gini_bounds():
    assert gini(np.full(100, 2.0)) == pytest.approx(0.0, abs=1e-9)
    concentrated = np.concatenate([np.zeros(9999), [1.0]])
    assert gini(concentrated) > 0.99
    rng = np.random.default_rng(1)
    g = gini(rng.lognormal(sigma=1.0, size=20000))
    # closed form for lognormal: 2*Phi(sigma/sqrt 2) - 1 ~ 0.5205
    assert abs(g - 0.5205) < 0.02


def test_wealth_stats_weighted_matches_expanded():
    rng = np.random.default_rng(2)
    d = rng.lognormal(size=300)
    reps = rng.integers(1, 6, size=300)
    ws = wealth_stats(d, weights=reps)
    we = wealth_stats(np.repeat(d, reps))
    assert ws.mean == pytest.approx(we.mean)
    assert ws.std == pytest.approx(we.std)
    assert ws.median == pytest.approx(we.median, rel=1e-2)


def test_histogram_sample_collapses_states():
    grid = np.array([0.0, 1.0, 2.0])
    masses = np.array([[0.1, 0.2], [0.3, 0.1], [0.2, 0.1]])
    g, m = histogram_sample(grid, masses)
    np.testing.assert_allclose(m, [0.3, 0.4, 0.3])
    s = wealth_stats(g, weights=m)
    assert s.mean == pytest.approx(1.0)


def test_lorenz_distance_zero_for_identical():
    d = np.random.default_rng(3).lognormal(size=500)
    assert lorenz_distance(d, d) == pytest.approx(0.0)


def test_scf_loader_missing_raises(tmp_path, monkeypatch):
    monkeypatch.delenv("SCF_WEALTH_CSV", raising=False)
    with pytest.raises(FileNotFoundError):
        load_scf_wealth_weights()
    p = tmp_path / "scf.csv"
    p.write_text("wealth,weight\n1.0,2.0\n5.0,1.0\n")
    w, wt = load_scf_wealth_weights(str(p))
    np.testing.assert_allclose(w, [1.0, 5.0])
    np.testing.assert_allclose(wt, [2.0, 1.0])


def test_vendored_scf_lorenz_reproduces_reference_golden():
    """The SCF curve vendored from the reference's committed vector figure
    must reproduce the reference's printed Lorenz-vs-SCF golden: the
    Euclidean distance between the vendored SCF curve and the reference's
    own simulated curve (both recovered from the same figure) is 0.9714
    (``Aiyagari-HARK.py:332-333``, BASELINE.md).  This pins the LAST
    reference golden — VERDICT r2 next-round item 1."""
    scf = load_scf_lorenz()
    np.testing.assert_allclose(scf.pctiles, np.linspace(0.01, 0.999, 15),
                               atol=1e-9)                # Aiyagari-HARK.py:312
    d = float(np.sqrt(np.sum((scf.scf_shares - scf.ref_sim_shares) ** 2)))
    assert d == pytest.approx(0.9714, abs=5e-4)
    # sanity on the recovered curve itself: monotone after the debtor
    # bottom, top-percentile share ~0.896 (top 0.1% hold the rest), and the
    # bottom shares slightly negative (SCF net worth includes debtors)
    assert scf.scf_shares[0] < 0.0
    assert np.all(np.diff(scf.scf_shares[3:]) > 0)
    assert scf.scf_shares[-1] == pytest.approx(0.8957, abs=1e-3)


def test_lorenz_distance_vs_scf_closed_form():
    """Equal wealth has Lorenz = diagonal, so the distance to the vendored
    SCF curve has a closed form computable directly from the CSV."""
    scf = load_scf_lorenz()
    expected = float(np.sqrt(np.sum((scf.scf_shares - scf.pctiles) ** 2)))
    d = lorenz_distance_vs_scf(np.full(5000, 4.0))
    assert d == pytest.approx(expected, abs=1e-3)


def test_synthetic_scf_smoke_path():
    """The documented SCF stand-in keeps the Lorenz-vs-SCF pipeline alive
    without the real data (VERDICT r1 missing-item 5): deterministic,
    top-heavy (Gini near the U.S. net-worth ~0.8), and usable end-to-end
    through lorenz_distance."""
    from aiyagari_hark_tpu.utils.stats import synthetic_scf_wealth

    w1, wt1 = synthetic_scf_wealth()
    w2, _ = synthetic_scf_wealth()
    np.testing.assert_array_equal(w1, w2)          # seeded
    assert 0.75 < gini(w1, wt1) < 0.9
    pct = np.linspace(0.01, 0.999, 15)             # Aiyagari-HARK.py:312
    sim = np.random.default_rng(5).lognormal(sigma=0.7, size=2000)
    d = lorenz_distance(sim, w1, weights_b=wt1, percentiles=pct)
    # an Aiyagari-like (too equal) wealth sample sits far from the SCF-like
    # curve -- the reference's golden vs real SCF is 0.9714
    assert 0.5 < d < 2.0
