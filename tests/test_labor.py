"""Endogenous labor supply (models/labor.py).

Oracles: the household optimality conditions themselves (Euler and
intratemporal FOC residuals at off-knot evaluation points), exactness of
the Newton-solved constrained region, the separable-preferences wealth
effect (richer households work less), the Frisch elasticity comparative
static, and general-equilibrium market clearing."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.labor import (
    build_labor_model,
    hours_from_foc,
    labor_policy_at,
    solve_labor_equilibrium,
    solve_labor_household,
)
from aiyagari_hark_tpu.ops.utility import marginal_utility

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


ALPHA, DELTA, BETA, CRRA = 0.36, 0.08, 0.96, 2.0
R, W = 1.03, 1.2


@pytest.fixture(scope="module")
def model():
    return build_labor_model(frisch=1.0, labor_weight=12.0,
                             labor_states=3, a_count=40, dist_count=120)


@pytest.fixture(scope="module")
def policy(model):
    pol, it, diff = solve_labor_household(R, W, model, BETA, CRRA,
                                          tol=1e-9)
    assert float(diff) < 1e-9
    return pol


def test_euler_and_intratemporal_residuals(model, policy):
    """At off-knot interior points the interpolated policy must satisfy
    both FOCs up to interpolation error: u'(c) = beta R E u'(c') and
    chi n^(1/nu) = W e u'(c)."""
    a = jnp.linspace(1.0, 15.0, 60)          # interior, unconstrained
    c, n, a_next = labor_policy_at(policy, a, R, W, model, CRRA)
    # next-period consumption state by state at a' (clip to the grid)
    c_next, _, _ = labor_policy_at(
        policy, jnp.clip(a_next.reshape(-1), 0.0, 50.0), R, W, model,
        CRRA)
    c_next = c_next.reshape(a.shape[0], -1, c_next.shape[1])  # [P, N, N']
    evp = BETA * R * jnp.einsum("pnm,nm->pn",
                                marginal_utility(c_next, CRRA),
                                model.base.transition)
    euler_rel = np.asarray(jnp.abs(marginal_utility(c, CRRA) / evp - 1.0))
    assert euler_rel.max() < 5e-3
    intra = np.asarray(jnp.abs(
        hours_from_foc(c, model.base.labor_levels[None, :], W, model,
                       CRRA) / n - 1.0))
    assert intra.max() < 5e-3


def test_constrained_region_is_exact(model, policy):
    """Where the borrowing constraint binds: savings exactly at the
    limit, and the static FOC solved to Newton precision (no
    interpolation in the constrained region)."""
    a = jnp.asarray([0.0, 0.002, 0.01])
    c, n, a_next = labor_policy_at(policy, a, R, W, model, CRRA)
    first_knot = np.asarray(policy.a_knots[:, 0])
    constrained = np.asarray(a)[:, None] < first_knot[None, :]
    assert constrained.any(), "pick smaller a: nothing binds"
    np.testing.assert_allclose(np.asarray(a_next)[constrained], 0.0,
                               atol=1e-12)
    # budget + FOC residual at the Newton solution
    e = np.asarray(model.base.labor_levels)
    cc, nn = np.asarray(c), np.asarray(n)
    budget = R * np.asarray(a)[:, None] + W * e[None, :] * nn - cc
    np.testing.assert_allclose(budget[constrained], 0.0, atol=1e-8)
    foc = (float(model.labor_weight)
           * nn ** (1.0 / float(model.frisch))
           - W * e[None, :] * cc ** (-CRRA))
    np.testing.assert_allclose(foc[constrained], 0.0, atol=1e-7)


def test_wealth_effect_on_hours(policy):
    """Separable preferences: hours fall with wealth along every
    productivity state's knot line."""
    n_knots = np.asarray(policy.n_knots)
    assert (np.diff(n_knots, axis=1) < 1e-12).all()


def test_frisch_elasticity_comparative_static(model):
    """Higher Frisch elasticity -> hours respond more to productivity:
    cross-state hours dispersion at fixed wealth rises with nu."""
    stiff = build_labor_model(frisch=0.2, labor_weight=12.0,
                              labor_states=3, a_count=40, dist_count=120)
    pol_stiff, _, _ = solve_labor_household(R, W, stiff, BETA, CRRA)
    pol_elastic, _, _ = solve_labor_household(R, W, model, BETA, CRRA)
    a = jnp.asarray([5.0])
    _, n_s, _ = labor_policy_at(pol_stiff, a, R, W, stiff, CRRA)
    _, n_e, _ = labor_policy_at(pol_elastic, a, R, W, model, CRRA)
    spread = lambda n: float(n.max() - n.min())   # noqa: E731
    assert spread(np.asarray(n_e)) > 2.0 * spread(np.asarray(n_s))


@pytest.fixture(scope="module")
def equilibrium(model):
    return solve_labor_equilibrium(model, BETA, CRRA, ALPHA, DELTA)


def test_equilibrium_clears(model, equilibrium):
    eq = equilibrium
    assert abs(float(eq.excess)) < 1e-6 * float(eq.capital)
    assert 0.0 < float(eq.r_star) < 1.0 / BETA - 1.0
    assert 0.05 < float(eq.mean_hours) < 1.5
    # capital-output consistency: K/Y = s/delta
    y = float(eq.capital) ** ALPHA * float(eq.effective_labor) ** (
        1 - ALPHA)
    np.testing.assert_allclose(float(eq.saving_rate),
                               DELTA * float(eq.capital) / y, rtol=1e-10)


def test_equilibrium_is_jittable(model):
    f = jax.jit(lambda: solve_labor_equilibrium(
        model, BETA, CRRA, ALPHA, DELTA, max_bisect=25))
    res = f()
    assert np.isfinite(float(res.r_star))


# ---------------------------------------------------------------------------
# Transition dynamics with endogenous hours
# ---------------------------------------------------------------------------


def test_labor_transition_steady_state_invariance(model, equilibrium):
    """No shock + stationary start: the joint (K, L) path must sit at
    the steady state throughout."""
    from aiyagari_hark_tpu.models.labor import solve_labor_transition

    eq = equilibrium
    res = solve_labor_transition(model, BETA, CRRA, ALPHA, DELTA,
                                 eq.distribution, eq.policy, eq.capital,
                                 eq.effective_labor, horizon=50)
    assert bool(res.converged)
    np.testing.assert_allclose(np.asarray(res.k_path),
                               float(eq.capital), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(res.l_path),
                               float(eq.effective_labor), rtol=1e-5)


def test_labor_transition_rbc_hallmarks(model, equilibrium):
    """A transitory TFP impulse with endogenous hours: hours rise on
    impact (substitution beats the wealth effect), output amplifies
    above the shock itself, capital is predetermined then humps — the
    RBC pattern the fixed-labor transition cannot produce."""
    from aiyagari_hark_tpu.models.labor import solve_labor_transition

    eq = equilibrium
    horizon = 80
    dz = 0.01 * 0.8 ** jnp.arange(horizon)
    res = solve_labor_transition(model, BETA, CRRA, ALPHA, DELTA,
                                 eq.distribution, eq.policy, eq.capital,
                                 eq.effective_labor, horizon=horizon,
                                 prod_path=1.0 + dz)
    assert bool(res.converged)
    h = np.asarray(res.hours_path)
    h_ss = float(eq.mean_hours)
    assert h[0] > h_ss * 1.0005            # procyclical hours on impact
    y = np.asarray(res.y_path)
    y_ss = y[-1]
    assert (y[0] / y_ss - 1.0) > 0.01      # amplification above dZ=1%
    k = np.asarray(res.k_path)
    k_ss = float(eq.capital)
    np.testing.assert_allclose(k[0], k_ss, rtol=1e-6)  # predetermined
    assert k[1:40].max() > k_ss * 1.001    # investment boom
    np.testing.assert_allclose(k[-1], k_ss, rtol=5e-3)  # reversion
    assert abs(h[-1] / h_ss - 1.0) < 5e-3
