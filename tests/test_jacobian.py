"""Sequence-space Jacobians (models/jacobian.py).

Oracles: finite differences of the exact discretized path map (autodiff
must match them to float precision), the nonlinear MIT-shock solver
(the linear IRF must match it to first order in the shock size), and the
structural zero/sign pattern economics pins down (predetermined K_0,
anticipation effects, substitution response of consumption)."""

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.models.jacobian import (
    business_cycle_moments,
    household_jacobians,
    innovation_irf,
    linear_impulse_response,
    sequence_jacobians,
    simulate_linear,
)
from aiyagari_hark_tpu.models.transition import (
    household_path_response,
    solve_transition,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')

ALPHA, DELTA, BETA, CRRA = 0.36, 0.08, 0.96, 2.0
HORIZON = 50


@pytest.fixture(scope="module")
def steady_state():
    model = build_simple_model(labor_states=3, a_count=30, dist_count=120)
    eq = solve_bisection_equilibrium(model, BETA, CRRA, ALPHA, DELTA)
    return model, eq


@pytest.fixture(scope="module")
def jacobians(steady_state):
    model, eq = steady_state
    return sequence_jacobians(model, BETA, CRRA, ALPHA, DELTA, eq, HORIZON)


def test_household_jacobian_matches_finite_differences(steady_state):
    """Autodiff differentiates the exact discretized program, so a central
    finite difference of the same map must agree to O(h^2) — the tightest
    oracle available, independent of any economics."""
    model, eq = steady_state
    T = 12
    r_flat = jnp.full((T,), eq.r_star)
    w_flat = jnp.full((T,), eq.wage)
    hh = household_jacobians(model, BETA, CRRA, eq, T)
    h = 1e-6
    for t in (0, 4, T - 1):
        bump = jnp.zeros(T).at[t].set(h)
        k_up, c_up = household_path_response(
            r_flat + bump, w_flat, model, BETA, CRRA, eq.distribution,
            eq.policy)
        k_dn, c_dn = household_path_response(
            r_flat - bump, w_flat, model, BETA, CRRA, eq.distribution,
            eq.policy)
        np.testing.assert_allclose(np.asarray(hh.k_r[:, t]),
                                   np.asarray((k_up - k_dn) / (2 * h)),
                                   atol=5e-4, rtol=5e-4)
        np.testing.assert_allclose(np.asarray(hh.c_r[:, t]),
                                   np.asarray((c_up - c_dn) / (2 * h)),
                                   atol=5e-4, rtol=5e-4)


def test_structural_pattern(jacobians):
    """K_0 is predetermined (zero first row); households respond TODAY to
    FUTURE price news (nonzero above-diagonal anticipation entries)."""
    jac = jacobians
    hh = jac.household
    np.testing.assert_allclose(np.asarray(hh.k_r[0]), 0.0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(hh.k_w[0]), 0.0, atol=1e-12)
    np.testing.assert_allclose(np.asarray(jac.g_k[0]), 0.0, atol=1e-10)
    # news at t=10 moves savings chosen at t=2 (K_3): anticipation
    assert abs(float(hh.k_r[3, 10])) > 1e-4
    # a wage windfall tomorrow raises consumption today (smoothing)
    assert float(hh.c_w[0, 1]) > 0.0
    # a current wage windfall raises current consumption less than
    # one-for-one (some is saved)
    assert 0.0 < float(hh.c_w[1, 1]) < 1.0


def test_linear_irf_matches_nonlinear_transition(steady_state, jacobians):
    """The linear IRF must converge to the nonlinear MIT-shock path as the
    shock shrinks: for a small TFP impulse the two capital paths agree to
    ~1% of the peak response."""
    model, eq = steady_state
    eps = 1e-3
    dz = eps * 0.8 ** jnp.arange(HORIZON)
    irf = linear_impulse_response(jacobians, dz)
    res = solve_transition(model, BETA, CRRA, ALPHA, DELTA,
                           init_dist=eq.distribution,
                           terminal_policy=eq.policy,
                           k_terminal=eq.capital, horizon=HORIZON,
                           prod_path=1.0 + dz, tol=1e-9)
    assert bool(res.converged)
    dk_nonlinear = np.asarray(res.k_path) - float(eq.capital)
    dk_linear = np.asarray(irf.dk)
    peak = np.abs(dk_nonlinear).max()
    assert peak > 0  # the shock does something
    np.testing.assert_allclose(dk_linear, dk_nonlinear, atol=0.015 * peak)
    dr_nonlinear = np.asarray(res.r_path) - float(eq.r_star)
    peak_r = np.abs(dr_nonlinear).max()
    np.testing.assert_allclose(np.asarray(irf.dr), dr_nonlinear,
                               atol=0.02 * peak_r)


def test_ge_jacobian_solves_fixed_point(jacobians):
    """G must satisfy the linearized equilibrium condition
    G = H_K G + H_Z (the implicit-function equation it was solved from) —
    and differ from the partial-equilibrium response H_Z (GE feedback)."""
    jac = jacobians
    lhs = np.asarray(jac.g_k)
    rhs = np.asarray(jac.h_k @ jac.g_k + jac.h_z)
    np.testing.assert_allclose(lhs, rhs, atol=1e-10)
    assert np.abs(lhs - np.asarray(jac.h_z)).max() > 1e-3


def test_irf_decays_to_zero(jacobians):
    """A transitory shock's GE response must die out: the far tail of the
    IRF is small relative to its peak (stationary equilibrium is locally
    stable under the K-path map)."""
    irf = linear_impulse_response(jacobians,
                                  0.01 * 0.7 ** jnp.arange(HORIZON))
    dk = np.abs(np.asarray(irf.dk))
    # K mean-reverts at ~0.93/period here, so 50 periods shed ~97% of the
    # peak; require monotone decay over the back half plus a 10% tail cap
    assert dk[-5:].max() < 0.10 * dk.max()
    back = dk[int(dk.argmax()):]
    assert (np.diff(back) < 1e-12).all()


def test_innovation_kernel_is_horizon_invariant(steady_state, jacobians):
    """Treating the date-0 innovation IRF as the MA kernel of a
    stationary process requires it not to depend on the truncation
    window: recompute the Jacobians on a longer horizon and check the
    kernels agree where they overlap (the terminal condition only
    contaminates the tail, which the decay test bounds)."""
    model, eq = steady_state
    jac_long = sequence_jacobians(model, BETA, CRRA, ALPHA, DELTA, eq,
                                  HORIZON + 12)
    k_short = np.asarray(innovation_irf(jacobians, 0.9).dk)
    k_long = np.asarray(innovation_irf(jac_long, 0.9).dk)
    np.testing.assert_allclose(k_short[:30], k_long[:30], rtol=0.02,
                               atol=1e-3 * np.abs(k_short).max())


def test_business_cycle_moments_match_simulation(jacobians):
    """Analytic MA moments vs a long simulated path of the same linear
    model: agreement to sampling error (fixed seed, 60k periods)."""
    import jax

    rho, sigma = 0.95, 0.007
    mom = business_cycle_moments(jacobians, rho, sigma)
    sim = simulate_linear(jacobians, rho, sigma, 60000,
                          jax.random.PRNGKey(7))
    for name in ("k", "c", "y", "z"):
        path = np.asarray(sim[name])
        assert abs(float(mom.std[name]) - path.std()) \
            < 0.12 * float(mom.std[name])
        ac1 = np.corrcoef(path[1:], path[:-1])[0, 1]
        assert abs(float(mom.autocorr1[name]) - ac1) < 0.05
    # z is the exogenous AR(1): its analytic moments are textbook, up to
    # kernel truncation at T (tail variance share rho^(2T)/(1-rho^2-term)
    # ~ 0.6% here — the documented accuracy limit of the T=50 window)
    np.testing.assert_allclose(float(mom.std["z"]),
                               sigma / np.sqrt(1 - rho ** 2), rtol=8e-3)
    np.testing.assert_allclose(float(mom.autocorr1["z"]), rho, atol=5e-3)


def test_fit_shock_process_recovers_truth(jacobians):
    """Self-consistency of sequence-space estimation: generate output
    moments at known (rho, sigma), re-estimate by gradient descent
    through the analytic moments, recover the truth."""
    from aiyagari_hark_tpu.models.jacobian import fit_shock_process

    rho_true, sigma_true = 0.92, 0.011
    mom = business_cycle_moments(jacobians, rho_true, sigma_true)
    fit = fit_shock_process(jacobians, mom.std["y"], mom.autocorr1["y"])
    assert bool(fit.converged), float(fit.loss)
    np.testing.assert_allclose(float(fit.rho), rho_true, atol=2e-4)
    np.testing.assert_allclose(float(fit.sigma_eps), sigma_true,
                               rtol=2e-3)


@pytest.fixture(scope="module")
def labor_jacobians():
    from aiyagari_hark_tpu.models.jacobian import labor_sequence_jacobians
    from aiyagari_hark_tpu.models.labor import (
        build_labor_model,
        solve_labor_equilibrium,
    )

    model = build_labor_model(frisch=1.0, labor_weight=12.0,
                              labor_states=3, a_count=24, dist_count=80)
    eq = solve_labor_equilibrium(model, BETA, CRRA, ALPHA, DELTA)
    jac = labor_sequence_jacobians(model, BETA, CRRA, ALPHA, DELTA, eq,
                                   40)
    return model, eq, jac


def test_labor_jacobians_match_nonlinear_transition(labor_jacobians):
    """The 2T-by-2T implicit-function solve must linearize the joint
    (K, L) transition: both paths match the nonlinear MIT solve to
    first order in the shock."""
    from aiyagari_hark_tpu.models.labor import solve_labor_transition

    model, eq, jac = labor_jacobians
    T = jac.g_k.shape[0]
    dz = 1e-3 * 0.8 ** jnp.arange(T)
    res = solve_labor_transition(model, BETA, CRRA, ALPHA, DELTA,
                                 eq.distribution, eq.policy, eq.capital,
                                 eq.effective_labor, T,
                                 prod_path=1.0 + dz, tol=1e-9)
    assert bool(res.converged)
    dk_nl = np.asarray(res.k_path) - float(eq.capital)
    dl_nl = np.asarray(res.l_path) - float(eq.effective_labor)
    dk_lin = np.asarray(jac.g_k @ dz)
    dl_lin = np.asarray(jac.g_l @ dz)
    assert np.abs(dk_lin - dk_nl).max() < 0.02 * np.abs(dk_nl).max()
    assert np.abs(dl_lin - dl_nl).max() < 0.02 * np.abs(dl_nl).max()


def test_hours_cyclicality_depends_on_persistence(labor_jacobians):
    """The labor economy's signature pattern: hours respond positively
    to TRANSITORY TFP (substitution effect) but turn countercyclical as
    shock persistence rises (the wealth effect of a long-lived
    productivity gain takes over) — corr(hours, Y) is monotone
    decreasing in rho, positive at 0.2, negative at 0.95, and the
    impact response of the hours kernel is positive for transitory
    shocks."""
    from aiyagari_hark_tpu.models.jacobian import (
        labor_business_cycle_moments,
    )

    _, _, jac = labor_jacobians
    corrs = [float(labor_business_cycle_moments(jac, rho,
                                                0.007).corr_with_y["h"])
             for rho in (0.2, 0.5, 0.8, 0.95)]
    assert corrs[0] > 0.5
    assert corrs[-1] < -0.5
    assert all(a > b for a, b in zip(corrs, corrs[1:]))
    kern_h = np.asarray(jac.g_h @ (0.5 ** jnp.arange(jac.g_h.shape[0])))
    assert kern_h[0] > 0  # substitution wins on impact
    # consumption smoother than output here too
    mom = labor_business_cycle_moments(jac, 0.95, 0.007)
    assert float(mom.std["c"]) < float(mom.std["y"])


def test_business_cycle_facts(jacobians):
    """The linearized Aiyagari economy reproduces the qualitative RBC
    facts: consumption is smoother than output, both procyclical, capital
    more persistent than output."""
    mom = business_cycle_moments(jacobians, 0.95, 0.007)
    assert float(mom.std["c"]) < float(mom.std["y"])
    assert float(mom.corr_with_y["c"]) > 0.5
    assert float(mom.autocorr1["k"]) > float(mom.autocorr1["y"])
    assert float(mom.autocorr1["k"]) > 0.95
