"""Checkpoint-layer satellites of ISSUE 3: crash-consistent artifact
writers, orphaned-tmp GC, and COMMITTED goldens for every historical KS
checkpoint layout.

The goldens (tests/data/checkpoints/ks_layout_v{1,2,3}.npz) are frozen
files written by the historical layouts' field sets under the class name
the old code actually used (``KSCheckpoint`` — the treedef embeds the
writer's class name).  Regenerating them in-test would let a future
``save_pytree`` change mask a migration break (round-3's dead-migration
regression: every tier raised on the class name before structure was ever
considered); loading committed bytes cannot."""

import json
import os
import time

import numpy as np
import pytest

from aiyagari_hark_tpu.utils.checkpoint import (
    atomic_write_json,
    atomic_write_text,
    gc_orphaned_tmp,
    load_ks_checkpoint,
)

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data",
                    "checkpoints")


# -- migration goldens ------------------------------------------------------


@pytest.mark.parametrize("version", [1, 2, 3])
def test_ks_checkpoint_migration_goldens(version):
    """Every historical layout must keep loading through the migration
    tiers (``_KSCheckpointV1/V2/V3`` + ``_canonical_treedef``), with the
    documented conservative defaults for fields the old layout lacks —
    so the next schema bump cannot silently break old checkpoints."""
    ck = load_ks_checkpoint(
        os.path.join(DATA, f"ks_layout_v{version}.npz"))
    # common payload, identical across the golden set
    np.testing.assert_array_equal(ck.intercept, [0.11, 0.22])
    np.testing.assert_array_equal(ck.slope, [0.95, 1.05])
    assert int(ck.iteration) == 5 and int(ck.seed) == 2
    assert bool(ck.converged) and int(ck.fingerprint) == 99
    # per-tier defaults: missing secant memory re-probes (NaN), missing
    # distance/residual are +inf so a migrated "converged" checkpoint can
    # never short-circuit a resume against the CURRENT tolerance
    if version == 1:
        assert np.isnan(ck.secant).all()
    else:
        np.testing.assert_array_equal(ck.secant, [0.5, -0.1, 0.4, 0.6])
    if version < 3:
        assert np.isinf(ck.last_distance)
    else:
        assert float(ck.last_distance) == 2e-3
    assert np.isinf(ck.last_residual)      # unknown for every old layout


# -- atomic artifact writers ------------------------------------------------


def test_atomic_write_json_roundtrip_and_replace(tmp_path):
    p = str(tmp_path / "record.json")
    atomic_write_json(p, {"a": 1, "b": [1.5, None]}, indent=1,
                      sort_keys=True)
    with open(p) as f:
        text = f.read()
    assert text.endswith("\n")
    assert json.loads(text) == {"a": 1, "b": [1.5, None]}
    # overwrite replaces atomically (no append, no residue)
    atomic_write_json(p, {"a": 2}, trailing_newline=False)
    with open(p) as f:
        assert json.load(f) == {"a": 2}
    # no tmp residue after successful writes
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_atomic_write_failure_keeps_previous_file(tmp_path):
    """The crash-consistency contract: a failed write leaves the PREVIOUS
    artifact intact and no tmp residue — never a truncated hybrid."""
    p = str(tmp_path / "record.json")
    atomic_write_json(p, {"ok": True})

    class Boom:
        """json.dumps raises on this before any bytes hit the target."""

    with pytest.raises(TypeError):
        atomic_write_json(p, {"bad": Boom()})
    with open(p) as f:
        assert json.load(f) == {"ok": True}
    assert [n for n in os.listdir(tmp_path) if n.endswith(".tmp")] == []


def test_atomic_write_text(tmp_path):
    p = str(tmp_path / "runtime.txt")
    atomic_write_text(p, "Total runtime: 1.0 seconds\n")
    with open(p) as f:
        assert f.read() == "Total runtime: 1.0 seconds\n"


# -- orphaned-tmp GC --------------------------------------------------------


def test_gc_orphaned_tmp_age_gate_and_logging(tmp_path):
    target = str(tmp_path / "ledger.npz")
    stale = tmp_path / "tmpdead01.npz.tmp"
    fresh = tmp_path / "tmplive02.json.tmp"
    stale.write_text("stranded by a hard kill")
    fresh.write_text("a concurrent writer's in-flight tmp")
    old = time.time() - 7200.0
    os.utime(stale, (old, old))
    with pytest.warns(UserWarning, match="orphaned checkpoint tmp"):
        removed = gc_orphaned_tmp(target, max_age_s=3600.0)
    assert [os.path.basename(r) for r in removed] == ["tmpdead01.npz.tmp"]
    assert not stale.exists()
    assert fresh.exists()                  # age gate: never race a writer
    # nothing left to collect -> no warning, empty result
    assert gc_orphaned_tmp(target, max_age_s=3600.0) == []


def test_gc_ignores_non_writer_files(tmp_path):
    """Only THIS module's writers' signatures (``tmp*.npz.tmp`` /
    ``.json.tmp`` / ``.txt.tmp``) are swept — other applications' mkstemp
    files in a shared directory (/tmp!) are not ours to delete, no matter
    how stale."""
    target = str(tmp_path / "ledger.npz")
    keepers = [tmp_path / "notes.tmp",        # user file ending in .tmp
               tmp_path / "tmpother777.tmp"]  # foreign mkstemp default
    old = time.time() - 7200.0
    for keep in keepers:
        keep.write_text("not ours")
        os.utime(keep, (old, old))
    assert gc_orphaned_tmp(target, max_age_s=0.0) == []
    assert all(k.exists() for k in keepers)


# -- the static atomic-writes lint (tier-1 hook) ----------------------------


def _load_lint():
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_atomic_writes",
        os.path.join(repo, "scripts", "check_atomic_writes.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_check_atomic_writes_lint_is_clean():
    """The package and entry points contain no bare write-mode open() /
    np.savez on artifact paths outside the blessed atomic writers — run
    here so a regression fails tier-1, not a code review."""
    mod, repo = _load_lint()
    findings = mod.scan(repo)
    assert findings == [], "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_check_atomic_writes_covers_serve_package():
    """ISSUE 4 satellite: the serving subsystem's on-disk store tier must
    be inside the lint's scope — pin the walk's coverage instead of
    trusting it silently."""
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    for required in ("aiyagari_hark_tpu/serve/store.py",
                     "aiyagari_hark_tpu/serve/service.py",
                     "aiyagari_hark_tpu/serve/batcher.py",
                     "aiyagari_hark_tpu/serve/metrics.py",
                     "aiyagari_hark_tpu/utils/checkpoint.py",
                     "bench.py"):
        assert required in rels, required


def test_check_atomic_writes_scan_fires_on_bare_write_in_serve(tmp_path):
    """End-to-end through the directory walk: a deliberately bare
    ``open(..., "w")`` dropped into a fake repo's ``serve/`` package is a
    finding (and a waived line is not)."""
    mod, _ = _load_lint()
    pkg = tmp_path / "aiyagari_hark_tpu" / "serve"
    pkg.mkdir(parents=True)
    (pkg / "bad_store.py").write_text(
        'def persist(path, text):\n'
        '    with open(path, "w") as f:\n'
        '        f.write(text)\n'
        'def waived(path, text):\n'
        '    with open(path, "w") as f:  # atomic-ok\n'
        '        f.write(text)\n')
    findings = mod.scan(str(tmp_path))
    assert [(rel.replace(os.sep, "/"), line)
            for rel, line, _ in findings] == [
        ("aiyagari_hark_tpu/serve/bad_store.py", 2)]


def test_check_atomic_writes_lint_catches_bare_write(tmp_path):
    """The lint actually fires on the pattern it guards against."""
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_atomic_writes",
        os.path.join(repo, "scripts", "check_atomic_writes.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = tmp_path / "writer.py"
    bad.write_text(
        # the exact pre-PR forms the lint exists to catch, parens and all
        'with open(path, "w") as f:\n    json.dump(rec, f)\n'
        'with open(p2, mode="wb") as f:\n    f.write(b"x")\n'
        'with open(p3, "w") as f:  # atomic-ok\n    pass\n'
        'np.savez(path, **arrays)\n'
        'np.savez(f, **arrays)\n'
        'with open(os.path.join(out_dir, "runtime.txt"), "w") as f:\n'
        '    f.write(x)\n'
        'with open(self.path(), "w") as f:\n    f.write(y)\n'
        # read-mode opens and w-leading filenames must NOT fire
        'with open(os.path.join(d, "warm.json")) as f:\n    pass\n'
        'with open("w.txt") as f:\n    pass\n')
    findings = mod.scan_file(str(bad), "writer.py")
    assert [line for _, line, _ in findings] == [1, 3, 7, 9, 11]


def test_check_atomic_writes_lint_catches_bare_append(tmp_path):
    """ISSUE 7 satellite: append-mode handles joined the ban — a
    buffered append flushes long records in chunks, so a SIGTERM
    between chunks tears mid-line.  ``checkpoint.append_jsonl`` is the
    blessed spelling; the JSONL record writers
    (``utils.timing.write_records_jsonl``) route through it."""
    mod, _ = _load_lint()
    bad = tmp_path / "appender.py"
    bad.write_text(
        'with open(path, "a") as f:\n    f.write(line)\n'
        'with open(p2, mode="ab") as f:\n    f.write(b"x")\n'
        'with open(p3, "a") as f:  # atomic-ok\n    pass\n'
        # read-mode and a-leading filenames must NOT fire
        'with open("a.txt") as f:\n    pass\n')
    findings = mod.scan_file(str(bad), "appender.py")
    assert [line for _, line, _ in findings] == [1, 3]


def test_check_atomic_writes_lint_catches_raw_os_open(tmp_path):
    """ISSUE 15 satellite: raw writable ``os.open`` descriptors joined
    the ban — an unblessed lease/publish writer would bypass every
    crash-consistency rule the blessed family encodes.  The blessed
    spellings (``append_jsonl``'s O_APPEND, ``acquire_lease``'s
    O_CREAT|O_EXCL) live in utils/checkpoint.py, which the lint
    exempts wholesale."""
    mod, _ = _load_lint()
    bad = tmp_path / "leaser.py"
    bad.write_text(
        'fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL)\n'
        'fd = os.open(p2, os.O_WRONLY | os.O_CREAT | os.O_APPEND, '
        '0o644)\n'
        'fd = os.open(p3, os.O_RDWR | os.O_TRUNC)\n'
        'fd = os.open(p4, os.O_CREAT)  # atomic-ok\n'
        # read-only descriptors must NOT fire
        'fd = os.open(path, os.O_RDONLY)\n')
    findings = mod.scan_file(str(bad), "leaser.py")
    assert [line for _, line, _ in findings] == [1, 2, 3]


def test_check_atomic_writes_lint_catches_raw_fsync(tmp_path):
    """ISSUE 18 satellite: raw ``os.fsync`` joined the ban — durability
    belongs to the blessed writers' ``durable=True`` path (file AND
    parent directory, in crash-safe order); a bare fsync elsewhere is a
    half-durable write that looks safe in review."""
    mod, _ = _load_lint()
    bad = tmp_path / "syncer.py"
    bad.write_text(
        'os.fsync(fd)\n'
        'os.fsync(f.fileno())  # atomic-ok: test-only barrier\n'
        # the read spelling must NOT fire
        'os.fstat(fd)\n')
    findings = mod.scan_file(str(bad), "syncer.py")
    assert [line for _, line, _ in findings] == [1]
    assert "durable=True" in findings[0][2]


def test_check_atomic_writes_covers_fleet_modules():
    """ISSUE 15 satellite: the fleet tier's modules (lease/publish
    writers, the HTTP worker) are inside the lint's scope — pinned
    instead of trusted."""
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    for required in ("aiyagari_hark_tpu/serve/fleet.py",
                     "aiyagari_hark_tpu/serve/loadgen.py",
                     "aiyagari_hark_tpu/serve/store.py"):
        assert required in rels, required


def test_check_atomic_writes_covers_timing_jsonl():
    """ISSUE 7 satellite: the bench/iteration JSONL writer module is in
    the lint's scope — pin it instead of trusting the walk."""
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    assert "aiyagari_hark_tpu/utils/timing.py" in rels
    assert "aiyagari_hark_tpu/obs/journal.py" in rels


def test_append_jsonl_appends_whole_lines(tmp_path):
    """The append-safe writer: grows the file without rewriting history,
    newline-terminates every record, and a torn tail (simulated partial
    final line) is skipped — not fatal — by the readers."""
    import warnings

    from aiyagari_hark_tpu.utils.checkpoint import append_jsonl
    from aiyagari_hark_tpu.utils.timing import (
        read_records_jsonl,
        write_records_jsonl,
    )

    p = str(tmp_path / "records.jsonl")
    write_records_jsonl(p, [{"i": 0}])
    write_records_jsonl(p, [{"i": 1}, {"i": 2}], append=True)
    append_jsonl(p, ['{"i": 3}'])
    assert read_records_jsonl(p) == [{"i": i} for i in range(4)]
    # torn tail: a hard kill mid-os.write leaves a partial last line
    with open(p, "ab") as f:  # atomic-ok: test simulates the torn tail
        f.write(b'{"i": 4, "part')
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert read_records_jsonl(p) == [{"i": i} for i in range(4)]
    assert any("unparseable" in str(x.message) for x in w)
