"""Multi-chip sharded sweep (ISSUE 11): the bit-identity property on the
8-virtual-device CPU mesh.

The load-bearing contract extends PR 2's scheduler parity to placement:
a sweep dispatched through the ``jit(shard_map)`` launcher
(``parallel.mesh.sharded_launcher``) over the ``cells`` axis must return
the root (r*), NaN masks, statuses, retries, and every iteration counter
BIT-identical to the 1-device run — both panels, a quarantined
(fault-injected) cell, and all three registered scenario families —
because each device runs the identical per-lane program on its lane
block and the only cross-device traffic is the output gather.  The ONE
exception is the PR 4 carve-out, now measured across program widths: the
within-lane aggregate contraction (capital, and its derived
saving-rate/excess) rides XLA reduction orders that differ between a
width-B and a width-B/n compilation of the same per-lane code, so it
agrees to reduction-order noise (~1e-12 relative; asserted tightly, not
bitwise).  A subprocess fixture additionally proves the property in a
FRESH interpreter whose host-device flag is set before jax initializes
(the forced-host-platform bootstrap ``bench.py --chips-scaling`` and the
driver's ``dryrun_multichip`` rely on).

Configs deliberately mirror test_sweep_scheduler / test_resilience /
test_scenarios so the 1-device references are jit-cache hits and each
test adds at most one new (sharded) executable to the suite's compile
bill.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from aiyagari_hark_tpu.parallel.mesh import (
    cells_mesh,
    make_mesh,
    mesh_axis_size,
    shard_map_compat,
    sharded_launcher,
)
from aiyagari_hark_tpu.parallel.sweep import run_sweep, run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig

# Same solver config + fault as tests/test_sweep_scheduler.py (shared
# jit/lru cache keys: the 1-device fault executables are already
# compiled there in tier-1).
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
TWO_PANEL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                        labor_sd=(0.2, 0.4))
FAULT = {"cell": 2, "at_iter": 2, "mode": "stall"}
# Same 4-cell config as tests/test_resilience.py's SMALL.
SMALL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                    schedule="balanced", n_buckets=2)
# Same Huggett / Epstein-Zin configs as tests/test_scenarios.py.
HKW = dict(a_count=12, dist_count=48, labor_states=3, r_tol=1e-5,
           max_bisect=20, egm_tol=1e-5, dist_tol=1e-9,
           borrow_limit=-2.0)
HCFG = SweepConfig(crra_values=(1.5, 3.0), rho_values=(0.3, 0.6),
                   schedule="balanced", n_buckets=2)
EKW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
           max_bisect=12, egm_tol=1e-5, dist_tol=1e-8, ez_rho=2.0)
ECFG = SweepConfig(crra_values=(2.0, 6.0), rho_values=(0.3, 0.6),
                   schedule="balanced", n_buckets=2)


# ---------------------------------------------------------------------------
# Mesh-layer helpers (no solves).
# ---------------------------------------------------------------------------

def test_mesh_axis_size_and_cells_mesh():
    assert mesh_axis_size(None, "cells") == 1
    mesh = cells_mesh()
    assert mesh_axis_size(mesh, "cells") == 8
    assert mesh_axis_size(mesh, "absent") == 1
    two = make_mesh(("cells",), (2,))
    assert mesh_axis_size(two, "cells") == 2


def test_sharded_launcher_memoized_per_fn_and_mesh():
    """One wrapped executable per (fn, mesh, axis): equal meshes hash
    equal, so repeated bucket/flush launches reuse the same jitted
    wrapper — the zero-new-compiles-on-replay contract's first half.
    (jit is lazy: nothing compiles here.)"""
    from aiyagari_hark_tpu.scenarios.registry import get_scenario
    from aiyagari_hark_tpu.utils.fingerprint import hashable_kwargs

    scn = get_scenario("aiyagari")
    fn = scn.batched_solver(np.dtype(np.float64),
                            hashable_kwargs(dict(KW)), None, False)
    m1 = make_mesh(("cells",), (2,))
    m2 = make_mesh(("cells",), (2,))     # equal grid -> equal hash
    assert sharded_launcher(fn, m1) is sharded_launcher(fn, m2)
    m4 = make_mesh(("cells",), (4,))
    assert sharded_launcher(fn, m1) is not sharded_launcher(fn, m4)


def test_panel_shim_is_the_mesh_shim():
    """The jax-version shard_map shim lives in ONE place now: the
    panel's private name must be the promoted ``mesh.shard_map_compat``
    (ISSUE 11 satellite — the 0.4.x/check_vma logic cannot fork)."""
    from aiyagari_hark_tpu.parallel import panel

    assert panel._shard_map is shard_map_compat


def test_mesh_auto_rejects_unknown_string():
    with pytest.raises(ValueError, match="auto"):
        run_table2_sweep(SMALL, mesh="all-of-them", **KW)


def test_resolve_mesh_contract():
    """One mesh-argument rule for sweep AND serve: None passes through,
    "auto" builds the all-device mesh, a mesh that does not define the
    lane axis is rejected loudly (it would otherwise silently run
    unsharded at shard count 1)."""
    from aiyagari_hark_tpu.parallel.mesh import resolve_mesh
    from aiyagari_hark_tpu.serve import EquilibriumService

    assert resolve_mesh(None) is None
    auto = resolve_mesh("auto")
    assert mesh_axis_size(auto, "cells") == 8
    wrong = make_mesh(("lanes",), (2,))
    with pytest.raises(ValueError, match="lane axis"):
        resolve_mesh(wrong, "cells")
    with pytest.raises(ValueError, match="lane axis"):
        EquilibriumService(start_worker=False, mesh=wrong)
    with pytest.raises(ValueError, match="lane axis"):
        run_table2_sweep(SMALL, mesh=wrong, **KW)


# ---------------------------------------------------------------------------
# Bit-identity properties on the session's 8-device mesh.
# ---------------------------------------------------------------------------

def assert_sharded_contract(a, b):
    """The sharded == 1-device contract: root/status/retries/counters/
    masks bitwise; the aggregate-contraction fields (capital and its
    derived saving rate / excess) to reduction-order noise — the PR 4
    eager-vs-vmap carve-out, measured across program widths."""
    assert np.array_equal(a.r_star_pct, b.r_star_pct, equal_nan=True)
    assert np.array_equal(a.status, b.status)
    assert np.array_equal(a.retries, b.retries)
    assert np.array_equal(a.egm_iters, b.egm_iters)
    assert np.array_equal(a.dist_iters, b.dist_iters)
    assert np.array_equal(a.bisect_iters, b.bisect_iters)
    for f in ("capital", "saving_rate_pct", "excess"):
        x, y = np.asarray(getattr(a, f)), np.asarray(getattr(b, f))
        assert np.array_equal(np.isnan(x), np.isnan(y)), f
        ok = ~np.isnan(x)
        # atol floor: excess is a near-zero market-clearing residual, so
        # the reduction-order noise must be measured against the
        # aggregate's scale (capital ~ O(5)), not the residual's
        np.testing.assert_allclose(x[ok], y[ok], rtol=1e-9, atol=1e-8,
                                   err_msg=f)


def test_sharded_sweep_bit_identical_with_quarantined_cell():
    """Both Table II panels through the shard_map launcher on 8 devices,
    locked AND balanced, vs the 1-device lock-step reference — values,
    NaN masks, statuses, counters all bit-equal, including the failed
    (stalled, unretried) cell's NaN mask.  The two sharded schedules pad
    to the same shape-8 launch, so this costs ONE new executable."""
    mesh = cells_mesh()
    ref = run_table2_sweep(TWO_PANEL.replace(schedule="locked"),
                           inject_fault=FAULT, max_retries=0, **KW)
    sharded_locked = run_table2_sweep(
        TWO_PANEL.replace(schedule="locked"), mesh=mesh,
        inject_fault=FAULT, max_retries=0, **KW)
    assert_sharded_contract(ref, sharded_locked)
    sharded_balanced = run_table2_sweep(
        TWO_PANEL.replace(schedule="balanced", n_buckets=2), mesh=mesh,
        inject_fault=FAULT, max_retries=0, **KW)
    assert_sharded_contract(ref, sharded_balanced)
    # the two sharded schedules pad to the SAME shape-8 launch of the
    # same executable, so between THEMSELVES they are fully bitwise
    assert np.array_equal(sharded_locked.capital,
                          sharded_balanced.capital, equal_nan=True)
    assert sharded_balanced.bucket is not None
    assert np.isnan(sharded_balanced.r_star_pct[FAULT["cell"]])
    assert len(sharded_balanced.failed_cells()) == 1


def test_sharded_sweep_bit_identical_other_scenarios():
    """Every registered family rides the one scenario-generic sharding
    pass: huggett and epstein_zin rows obey the sharded contract between
    the 8-device mesh and the 1-device run (aiyagari is pinned above) —
    root/status/counters bitwise by name, the remaining value columns
    (aggregate contractions) to reduction-order noise."""
    mesh = cells_mesh()
    for name, cfg, kw in (("huggett", HCFG, HKW),
                          ("epstein_zin", ECFG, EKW)):
        res_1 = run_sweep(name, sweep=cfg, **kw)
        res_n = run_sweep(name, sweep=cfg, mesh=mesh, **kw)
        schema = res_1.schema
        exact = ((schema.root, schema.status) + tuple(schema.counters)
                 + tuple(schema.phases or ()))
        for f in schema.fields:
            x, y = res_1.col(f), res_n.col(f)
            if f in exact:
                assert np.array_equal(x, y, equal_nan=True), (name, f)
            else:
                assert np.array_equal(np.isnan(x), np.isnan(y)), (name, f)
                ok = ~np.isnan(x)
                np.testing.assert_allclose(x[ok], y[ok], rtol=1e-9,
                                           err_msg=f"{name}:{f}")
        assert np.array_equal(res_1.status, res_n.status), name
        assert np.array_equal(res_1.retries, res_n.retries), name


def test_mesh_auto_resolves_to_all_devices():
    """``mesh="auto"`` builds the cells mesh over every local device and
    returns the same answer as no mesh at all (sharded contract)."""
    res_auto = run_table2_sweep(SMALL, mesh="auto", **KW)
    res_none = run_table2_sweep(SMALL, **KW)
    assert_sharded_contract(res_none, res_auto)


# ---------------------------------------------------------------------------
# Fresh-interpreter subprocess proof (the forced-host-device bootstrap).
# ---------------------------------------------------------------------------

_CHILD = r"""
import json, os, sys
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache
enable_compilation_cache()
import numpy as np
from aiyagari_hark_tpu.parallel.mesh import cells_mesh
from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.utils.config import SweepConfig

kw = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
cfg = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                  schedule="balanced", n_buckets=2)
res_1 = run_table2_sweep(cfg, **kw)
mesh = cells_mesh()
res_8 = run_table2_sweep(cfg, mesh=mesh, **kw)
print(json.dumps({
    "n_devices": len(jax.devices()),
    "mesh_cells": int(mesh.shape["cells"]),
    "bit_identical": bool(
        np.array_equal(res_1.r_star_pct, res_8.r_star_pct)
        and np.array_equal(res_1.status, res_8.status)
        and np.array_equal(res_1.egm_iters, res_8.egm_iters)
        and np.array_equal(res_1.dist_iters, res_8.dist_iters)),
}))
"""


@pytest.fixture(scope="module")
def forced_host_report():
    """Run the sharded-vs-1-device comparison in a FRESH interpreter that
    sets ``--xla_force_host_platform_device_count`` BEFORE jax
    initializes — the exact bootstrap ``bench.py --chips-scaling`` and
    ``dryrun_multichip`` depend on, which an in-suite test (whose
    backend the conftest already initialized) cannot exercise.  Shares
    the persistent compile cache with the in-process tests above, so the
    child pays imports + solves, not fresh XLA compiles."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)            # the child must set it itself
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", _CHILD],
                         capture_output=True, text=True, timeout=560,
                         env=env, cwd=os.path.dirname(
                             os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_forced_host_subprocess_sharded_bit_identity(forced_host_report):
    rep = forced_host_report
    assert rep["n_devices"] == 8
    assert rep["mesh_cells"] == 8
    assert rep["bit_identical"] is True
