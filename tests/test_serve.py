"""Equilibrium serving subsystem (ISSUE 4): the bit-identity property
test, failure isolation, deterministic batching, drain semantics, and the
threaded soak.

The load-bearing contract mirrors PR 2's scheduler parity: a served
result is bit-identical to a direct single-cell launch of the same
executable family with the same bracket seed, regardless of batch
packing, padding, or which other requests shared the launch — and a
failed (NONFINITE) cell raises a typed error on its own future without
poisoning batchmates."""

import threading

import numpy as np
import pytest

from aiyagari_hark_tpu.serve import (
    EquilibriumService,
    EquilibriumSolveFailed,
    MicroBatcher,
    ServeQueueFull,
    ServiceClosed,
    default_ladder,
    make_query,
)
from aiyagari_hark_tpu.solver_health import NONFINITE, is_failure
from aiyagari_hark_tpu.utils.resilience import (
    Interrupted,
    clear_interrupt,
    request_interrupt,
)

# The same tiny-cell configuration as tests/test_bench_smoke.py, so the
# suite shares compiled executables instead of paying fresh XLA compiles
# per file.
KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)


class FakeClock:
    """Deterministic injected clock for the deadline machinery."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def manual_service(**over):
    kw = dict(start_worker=False, max_batch=4, max_wait_s=60.0,
              ladder=(1, 2, 4))
    kw.update(over)
    return EquilibriumService(**kw)


def assert_rows_equal(a, b):
    """Full bit equality of two served/reference results' value fields."""
    assert (a.r_star, a.capital, a.labor) == (b.r_star, b.capital, b.labor)
    assert (a.bisect_iters, a.egm_iters, a.dist_iters) == (
        b.bisect_iters, b.egm_iters, b.dist_iters)
    assert a.status == b.status


# ---------------------------------------------------------------------------
# Bit-identity property test (the acceptance contract).
# ---------------------------------------------------------------------------

def test_mixed_batch_bit_identity():
    """One launch holding a near-hit warm lane, a cold lane, and padding,
    plus an exact hit served at submit: every request's result equals the
    direct single-cell solve with the same seed, bit for bit."""
    svc = manual_service(donor_cutoff=0.5)
    qa = make_query(3.0, 0.6, **KW)
    ra = svc.query(3.0, 0.6, **KW)           # seeds the store (cold)
    assert ra.path == "cold"

    # exact hit: resolves at submit, no launch, bits are the stored ones
    fhit = svc.submit(make_query(3.0, 0.6, **KW))
    assert fhit.done()
    assert_rows_equal(fhit.result(), ra)
    assert fhit.result().path == "hit"

    # mixed flush: two near neighbors + one far cold, 3 real lanes padded
    # to ladder shape 4
    fb = svc.submit(make_query(3.0, 0.65, **KW))    # near (donor: qa)
    fc = svc.submit(make_query(1.0, 0.0, **KW))     # far -> cold
    fd = svc.submit(make_query(3.0, 0.55, **KW))    # near
    assert svc.flush() == 1                         # ONE shared launch
    rb, rc, rd = fb.result(0), fc.result(0), fd.result(0)
    assert rb.path == "near" and rd.path == "near"
    assert rc.path == "cold"
    assert rb.bracket_init[2] > 0 and rc.bracket_init[2] == 0

    # the contract: same executable family, batch of 1, same seed ->
    # identical bits for every field, for every lane of the mixed batch
    for res, q in ((ra, qa),
                   (rb, make_query(3.0, 0.65, **KW)),
                   (rc, make_query(1.0, 0.0, **KW)),
                   (rd, make_query(3.0, 0.55, **KW))):
        ref = svc.reference_solve(q, bracket_init=res.bracket_init)
        assert_rows_equal(res, ref)

    # a pseudo-cold lane replays the exact cold trajectory: equilibrium
    # values match the bare cold program bit-for-bit; only the work
    # counters carry the two verification solves
    cold_ref = svc.reference_solve(make_query(1.0, 0.0, **KW))
    assert (rc.r_star, rc.capital, rc.labor, rc.status) == (
        cold_ref.r_star, cold_ref.capital, cold_ref.labor, cold_ref.status)
    assert rc.bisect_iters == cold_ref.bisect_iters + 2
    svc.close()


def test_served_bits_vs_eager_direct_call():
    """Against the un-vmapped eager ``solve_equilibrium_lean``: the root,
    labor, counters, and status are bit-identical; ``capital`` — the one
    cross-lane reduction — agrees to summation-order noise (DESIGN §8)."""
    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    svc = manual_service()
    res = svc.query(3.0, 0.6, **KW)
    d = solve_calibration_lean(3.0, 0.6, labor_sd=0.2,
                               bracket_init=res.bracket_init, **KW)
    assert res.r_star == float(d.r_star)
    assert res.labor == float(d.labor)
    assert res.bisect_iters == int(d.bisect_iters)
    assert res.egm_iters == int(d.egm_iters)
    assert res.dist_iters == int(d.dist_iters)
    assert res.status == int(d.status)
    assert abs(res.capital - float(d.capital)) <= 1e-9 * abs(res.capital)
    svc.close()


def test_nonfinite_cell_fails_its_future_not_the_batch():
    """Deterministic fault injection: the poisoned lane's future raises
    the typed ``EquilibriumSolveFailed``; batchmates' bits equal the
    fault-free direct solves; the failure is never cached."""
    svc = manual_service(inject_fault_mode="nan")
    qa = make_query(3.0, 0.6, **KW)
    qf = make_query(1.0, 0.3, fault_iter=0, **KW)
    qc = make_query(5.0, 0.9, **KW)
    fa, ff, fc = svc.submit(qa), svc.submit(qf), svc.submit(qc)
    assert svc.flush() == 1                         # one shared launch
    with pytest.raises(EquilibriumSolveFailed) as exc:
        ff.result(0)
    assert exc.value.status == NONFINITE
    assert is_failure(exc.value.status)
    # the failed calibration never became a cache entry (and a healthy
    # same-cell query later would still solve, not hit garbage)
    assert svc.store.get(make_query(1.0, 0.3, **KW).key()) is None
    # batchmates: bit-identical to the fault-free reference solves
    for fut, q in ((fa, qa), (fc, qc)):
        res = fut.result(0)
        ref = svc.reference_solve(
            make_query(q.crra, q.labor_ar, **KW),
            bracket_init=res.bracket_init)
        assert_rows_equal(res, ref)
    assert svc.metrics.failures == 1
    svc.close()


# ---------------------------------------------------------------------------
# Batching mechanics with a deterministic clock.
# ---------------------------------------------------------------------------

def test_default_ladder_shapes():
    assert default_ladder(8) == (1, 2, 4, 8)
    assert default_ladder(12) == (1, 2, 4, 8, 12)
    assert default_ladder(1) == (1,)
    b = MicroBatcher(max_batch=8)
    assert [b.pad_to(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]


def test_shard_ladder_rounds_to_device_multiples():
    """Multi-chip ladders (ISSUE 11): every shape rounds UP to a shard
    multiple and dedupes, so a sharded flush always divides the mesh."""
    from aiyagari_hark_tpu.serve import shard_ladder

    assert shard_ladder((1, 2, 4, 8), 1) == (1, 2, 4, 8)
    assert shard_ladder((1, 2, 4, 8), 4) == (4, 8)
    assert shard_ladder((1, 2, 4, 8), 8) == (8,)
    assert shard_ladder((1, 2, 4, 8, 12), 8) == (8, 16)
    assert shard_ladder((3,), 2) == (4,)
    with pytest.raises(ValueError):
        shard_ladder((1, 2), 0)
    b = MicroBatcher(max_batch=8, shard_multiple=4)
    assert b.ladder == (4, 8)
    assert [b.pad_to(n) for n in (1, 4, 5, 8)] == [4, 4, 8, 8]


def test_batcher_deadline_and_size_flush():
    clk = FakeClock()
    b = MicroBatcher(max_batch=4, max_wait_s=0.010, clock=clk)
    b.offer("g", "r0")
    assert b.pop_ready() == []                  # deadline not reached
    assert b.next_deadline() == pytest.approx(0.010)
    clk.advance(0.005)
    assert b.pop_ready() == []
    clk.advance(0.006)                          # past the deadline
    ready = b.pop_ready()
    assert ready == [("g", ["r0"])]
    # size-triggered: max_batch arrivals flush immediately, no deadline
    for i in range(4):
        b.offer("g", f"s{i}")
    assert b.pop_ready() == [("g", ["s0", "s1", "s2", "s3"])]
    assert b.depth() == 0


def test_batcher_bounded_queue():
    b = MicroBatcher(max_batch=4, max_queue=2, clock=FakeClock())
    b.offer("g", 1)
    b.offer("g", 2)
    with pytest.raises(ServeQueueFull):
        b.offer("g", 3, block=False)
    with pytest.raises(ServeQueueFull):
        b.offer("g", 3, timeout=0.01)
    assert b.depth() == 2


def test_service_deadline_with_injected_clock():
    clk = FakeClock()
    svc = manual_service(max_wait_s=0.010, clock=clk)
    fut = svc.submit(make_query(3.0, 0.6, **KW))
    assert svc.pump() == 0 and not fut.done()   # before the deadline
    clk.advance(0.011)
    assert svc.pump() == 1
    assert fut.result(0).path == "cold"
    svc.close()


def test_batch_occupancy_and_queue_metrics():
    svc = manual_service()
    for rho in (0.0, 0.3, 0.6):
        svc.submit(make_query(1.0, rho, **KW))
    svc.flush()                                  # 3 real lanes -> shape 4
    snap = svc.metrics.snapshot()
    assert snap["serve_batches"] == 1
    assert snap["serve_batch_occupancy"] == pytest.approx(0.75)
    assert snap["serve_queue_depth_peak"] == 3
    svc.close()


# ---------------------------------------------------------------------------
# Cache-hit contract (ISSUE 4 satellite: tier-1 smoke).
# ---------------------------------------------------------------------------

def test_sharded_service_bit_identical_and_zero_compiles_on_replay():
    """The PR 4 zero-compile smoke extended to the sharded batcher
    (ISSUE 11): a service over the 8-device mesh pads flushes to
    per-device multiples, serves bits identical to the 1-device service,
    resolves exact replays with zero XLA work, and a second same-shape
    cold wave is a pure executable-cache hit (one wrapped executable per
    ladder shape per solver group, mesh included)."""
    from aiyagari_hark_tpu.parallel.mesh import cells_mesh
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    mesh = cells_mesh()
    svc = manual_service(max_batch=8, ladder=(1, 2, 4, 8), mesh=mesh)
    assert svc.batcher.ladder == (8,)         # rounded to the mesh
    cells = [(s, r) for s in (1.0, 3.0)
             for r in (0.0, 0.3, 0.6, 0.9)]
    queries = [make_query(s, r, **KW) for s, r in cells]
    futs = [svc.submit(q) for q in queries]
    svc.flush()
    served = [f.result(0) for f in futs]
    # the PR 4 bit-identity reference: a batch-of-1 launch of the same
    # executable family with the same seed (cold here), unsharded
    for q, a in zip(queries, served):
        b = svc.reference_solve(q, bracket_init=a.bracket_init)
        assert_rows_equal(a, b)
        assert a.values == b.values           # the full packed row

    with CompileCounter() as c_hit:           # exact replay: pure hits
        for s, r in cells:
            fut = svc.submit(make_query(s, r, **KW))
            assert fut.done()
            fut.result(0)
    assert c_hit.compile_events == 0 and c_hit.cache_misses == 0

    # a second cold wave at the same ladder shape: zero NEW compiles —
    # the sharded launcher is memoized per (fn, mesh), so the warmed
    # multi-chip service still owns ONE executable per shape
    shifted = [(s, r, 0.4) for s, r in cells]
    with CompileCounter() as c_cold:
        futs = [svc.submit(make_query(s, r, labor_sd=sd, **KW))
                for s, r, sd in shifted]
        svc.flush()
        [f.result(0) for f in futs]
    assert c_cold.cache_misses == 0, c_cold.__dict__
    svc.close()


def test_second_identical_query_is_hit_with_zero_compiles():
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    svc = manual_service()
    first = svc.query(3.0, 0.6, **KW)
    assert first.path == "cold"
    with CompileCounter() as c:
        fut = svc.submit(make_query(3.0, 0.6, **KW))
        assert fut.done()                        # resolved at submit
        second = fut.result()
    assert c.compile_events == 0 and c.cache_misses == 0
    assert second.path == "hit"
    assert_rows_equal(first, second)
    snap = svc.metrics.snapshot()
    assert snap["serve_hit_rate"] == pytest.approx(0.5)
    assert snap["serve_hit_p50_ms"] is not None
    svc.close()


# ---------------------------------------------------------------------------
# Drain / shutdown / preemption semantics.
# ---------------------------------------------------------------------------

def test_close_drains_pending_futures():
    svc = manual_service()
    futs = [svc.submit(make_query(1.0, rho, **KW)) for rho in (0.0, 0.3)]
    svc.close(drain=True)
    for f in futs:
        assert not is_failure(f.result(0).status)
    with pytest.raises(ServiceClosed):
        svc.submit(make_query(1.0, 0.6, **KW))


def test_close_without_drain_fails_pending():
    svc = manual_service()
    fut = svc.submit(make_query(1.0, 0.45, **KW))
    svc.close(drain=False)
    with pytest.raises(ServiceClosed):
        fut.result(0)


def test_preemption_fails_pending_with_typed_interrupted():
    svc = manual_service()
    fut = svc.submit(make_query(1.0, 0.55, **KW))
    try:
        request_interrupt()
        with pytest.raises(Interrupted):
            svc.pump()
        with pytest.raises(Interrupted):
            fut.result(0)
    finally:
        clear_interrupt()
    # the service closed at the seam: no more submits
    with pytest.raises(ServiceClosed):
        svc.submit(make_query(1.0, 0.6, **KW))


def test_worker_preemption_fails_popped_and_queued_futures():
    """WORKER-mode preemption (the path a live service actually runs): a
    shutdown request observed at the worker's batch seam must fail every
    pending future — popped or still queued — with the typed
    ``Interrupted``, never leave a waiter hung through the preemption."""
    svc = EquilibriumService(max_batch=4, max_wait_s=60.0, ladder=(1, 2, 4))
    try:
        futs = [svc.submit(make_query(1.0, rho, **KW))
                for rho in (0.05, 0.15)]        # queued behind max_wait
        request_interrupt()
        for f in futs:
            with pytest.raises(Interrupted):
                f.result(10)                    # must FAIL, not hang
        with pytest.raises(ServiceClosed):
            svc.submit(make_query(1.0, 0.25, **KW))
    finally:
        clear_interrupt()
        svc.close()


def test_sweep_and_store_share_one_donor_rule():
    """The donor-ranking metric and margin rule are one implementation
    (``parallel.sweep.neighbor_distance``/``donor_margin``) — a drifted
    copy in the store would silently break batch/serving warm-start
    parity."""
    from aiyagari_hark_tpu.parallel.sweep import (
        donor_margin,
        neighbor_distance,
    )
    from aiyagari_hark_tpu.serve import SolutionStore, make_solution

    store = SolutionStore(capacity=8)
    cells = [(3.0, 0.60, 0.2), (3.0, 0.90, 0.2), (1.0, 0.65, 0.2)]
    roots = [0.035, 0.030, 0.040]
    for k, (cell, r) in enumerate(zip(cells, roots), start=1):
        row = np.asarray([r, 5.0, 0.9, 11.0, 500.0, 4000.0, 0.0])
        store.put(make_solution(cell, row, 7, k))
    query_cell, width, r_tol = (3.0, 0.65, 0.2), 0.12, 1e-4
    nom = store.nominate(query_cell, 7, width, r_tol)
    d = neighbor_distance(query_cell, np.asarray(cells))
    order = np.argsort(d, kind="stable")
    assert nom.donor_key == int(order[0]) + 1
    spread = abs(roots[int(order[0])] - roots[int(order[1])])
    assert nom.margin == donor_margin(spread, width, r_tol)


def test_fault_query_requires_fault_service():
    svc = manual_service()
    with pytest.raises(ValueError):
        svc.submit(make_query(1.0, 0.3, fault_iter=0, **KW))
    svc.close()


# ---------------------------------------------------------------------------
# Threaded soak (slow): hundreds of concurrent submits, shuffled arrivals.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_threaded_soak_shuffled_arrivals():
    """4 threads x 60 shuffled submits over a small lattice through a
    live worker thread — twice.  Wave 1 is an all-miss storm (every
    submit lands before the first solve resolves): every future resolves,
    every served result is bit-identical to the direct single-cell solve
    with its recorded seed, and warm answers sit within the bracket
    certificate of cold.  Wave 2 replays the same shuffled queries
    against the now-warm store: pure exact hits, bit-equal to wave 1."""
    rng = np.random.default_rng(1234)
    lattice = [(c, r) for c in (1.0, 3.0) for r in (0.0, 0.3, 0.6, 0.9)]
    queries = [lattice[i] for i in rng.integers(0, len(lattice), 240)]
    svc = EquilibriumService(max_batch=8, max_wait_s=0.002, max_queue=512)

    def storm():
        futs = [None] * len(queries)

        def submitter(tid):
            for i in range(tid, len(queries), 4):
                c, r = queries[i]
                futs[i] = svc.submit(make_query(c, r, **KW))

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return [f.result(120) for f in futs]

    wave1 = storm()

    # verify each distinct (key, seed) once against the direct reference,
    # and each distinct key once against the bare cold program
    r_tol = KW["r_tol"]
    seen = {}
    cold = {}
    for (c, r), res in zip(queries, wave1):
        assert not is_failure(res.status)
        q = make_query(c, r, **KW)
        sig = (res.key, res.bracket_init)
        if sig not in seen:
            seen[sig] = (svc.reference_solve(q, res.bracket_init)
                         if res.bracket_init is not None else None)
        ref = seen[sig]
        if ref is not None:
            assert_rows_equal(res, ref)
        if res.key not in cold:
            cold[res.key] = svc.reference_solve(q)
        assert abs(res.r_star - cold[res.key].r_star) <= 4.0 * r_tol

    # wave 2: same shuffled arrivals, warm store -> pure exact hits.  The
    # cached entry is the last wave-1 launch that wrote the key (duplicate
    # queries in different batches may differ at inner-solver noise), so
    # assert membership in wave 1's result set for the key.
    by_key = {}
    for res in wave1:
        by_key.setdefault(res.key, []).append(
            (res.r_star, res.capital, res.labor, res.bisect_iters,
             res.egm_iters, res.dist_iters, res.status))
    wave2 = storm()
    svc.close()
    for res in wave2:
        assert res.path == "hit"
        row = (res.r_star, res.capital, res.labor, res.bisect_iters,
               res.egm_iters, res.dist_iters, res.status)
        assert row in by_key[res.key]
    snap = svc.metrics.snapshot()
    assert snap["serve_requests"] == 2 * len(queries)
    assert snap["serve_failures"] == 0
    assert snap["serve_hit_rate"] >= 0.49      # wave 2 is all hits
