"""Equilibrium-level tests: market clearing, golden regression values, the
f32-vs-f64 1bp equivalence budget (BASELINE.md), and comparative statics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_calibration

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


# Reference context (BASELINE.md): the reference's KS-style run of the same
# calibration records r* = 4.178% with 350-agent Monte Carlo noise; Aiyagari's
# paper value is 4.09%.  Our deterministic fine-distribution solve gives
# 4.125% — the regression pin for this framework's CPU oracle.
GOLDEN_R_STAR = 0.041251


@pytest.fixture(scope="module")
def baseline():
    fn = jax.jit(lambda: solve_calibration(1.0, 0.3, labor_sd=0.2,
                                           dist_count=500))
    return fn()


def test_market_clears(baseline):
    assert abs(float(baseline.excess)) < 1e-6


def test_r_star_golden(baseline):
    assert abs(float(baseline.r_star) - GOLDEN_R_STAR) < 5e-5


def test_r_star_near_paper_and_reference(baseline):
    r_pct = float(baseline.r_star) * 100
    # Aiyagari Table II: 4.0912; reference notebook: 4.178
    assert 3.9 < r_pct < 4.3
    sr_pct = float(baseline.saving_rate) * 100
    # reference notebook savings rate: 23.649%
    assert 22.0 < sr_pct < 25.5


def test_f32_within_1bp_of_f64(baseline):
    """BASELINE.md equivalence target: |r*_TPU(f32) - r*_CPU(f64)| < 1 bp."""
    res32 = jax.jit(lambda: solve_calibration(
        1.0, 0.3, labor_sd=0.2, dist_count=500, dtype=jnp.float32,
        r_tol=1e-6, egm_tol=1e-5, dist_tol=1e-8))()
    diff = abs(float(res32.r_star) - float(baseline.r_star))
    assert diff < 1e-4, f"f32/f64 gap {diff*1e4:.2f} bp"
    assert res32.r_star.dtype == jnp.float32


def test_illinois_root_matches_bisect(baseline):
    """The alternative Illinois root-finder must land on the same
    equilibrium as bisection (both maintain a sign bracket to the same
    r_tol certificate) with fewer evaluations."""
    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean

    ill = solve_calibration_lean(1.0, 0.3, labor_sd=0.2, dist_count=500,
                                 root_method="illinois")
    # agreement is limited by inner-solve noise near the root (egm_tol
    # 1e-6 warm-started along different evaluation paths), not by the
    # 1e-10 bracket: observed ~5e-7 in r (≪ 0.01bp)
    np.testing.assert_allclose(float(ill.r_star), float(baseline.r_star),
                               atol=2e-6)
    # the module fixture's full solve uses the same r_tol bisection — its
    # iteration count is the bisect yardstick (no second cold solve)
    assert int(ill.bisect_iters) < int(baseline.bisect_iters)


def test_comparative_statics_crra():
    """More risk aversion -> more precautionary saving -> lower r*."""
    r = {}
    for crra in (1.0, 5.0):
        res = jax.jit(lambda c: solve_calibration(c, 0.3, dist_count=300))(crra)
        r[crra] = float(res.r_star)
    assert r[5.0] < r[1.0]


def test_comparative_statics_persistence():
    """More persistent income risk -> lower r*."""
    fn = jax.jit(lambda rho: solve_calibration(1.0, rho, dist_count=300))
    assert float(fn(0.9).r_star) < float(fn(0.0).r_star)


def test_vmap_over_cells_matches_serial():
    """A vmapped (crra, rho) batch — the Table II execution shape — agrees
    with per-cell solves."""
    crras = jnp.array([1.0, 3.0])
    rhos = jnp.array([0.0, 0.6])
    batched = jax.jit(jax.vmap(
        lambda c, r: solve_calibration(c, r, dist_count=200).r_star))
    rb = np.asarray(batched(crras, rhos))
    for i in range(2):
        ci, rhoi = float(crras[i]), float(rhos[i])
        ri = float(jax.jit(
            lambda c, r: solve_calibration(c, r, dist_count=200).r_star)(ci, rhoi))
        np.testing.assert_allclose(rb[i], ri, atol=1e-9)


def test_named_benchmark_configs():
    """BASELINE.json configs 1-2 run through the N-generic solver: the
    100-pt-grid baseline cell and the fine-grid 1000-pt x 15-state cell
    the reference's hard-coded N=7 machinery could never express
    (SURVEY.md §3.6-2)."""
    from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
    from aiyagari_hark_tpu.utils.config import (
        baseline_cell_kwargs,
        fine_grid_kwargs,
    )

    results = {}
    for name, kw in (("baseline", baseline_cell_kwargs()),
                     ("fine", fine_grid_kwargs())):
        crra, rho = kw.pop("crra"), kw.pop("labor_ar")
        res = jax.jit(lambda c, r, kw=kw: solve_calibration_lean(
            c, r, dtype=jnp.float32, **kw))(crra, rho)
        r_pct = float(res.r_star) * 100.0
        assert 3.0 < r_pct < 4.17, (name, r_pct)
        results[name] = r_pct
    # same economy at two resolutions: answers must be close, not equal
    assert abs(results["baseline"] - results["fine"]) < 0.1
