"""Lease-backend conformance suite (ISSUE 16 tentpole): ONE set of
election-semantics tests parameterized over every ``LeaseBackend``
implementation — the shared-directory default, the in-memory CAS model,
and the CAS served over loopback TCP — so "what a lease means" is pinned
by the suite, not by whatever one substrate happens to do.

Covered per backend: exactly-once election (sequential, threaded burst,
and — for the two backends real processes can share — a two-interpreter
concurrent-claim race), heartbeat keeping a live winner alive, TTL
reclaim of a dead one with re-election afterwards, the
reclaim-vs-heartbeat race (a beat that lands before the reclaim refuses
it), owner-checked release/heartbeat (a late waker can't delete a peer's
fresh lease), backward-clock clamping (negative age reads fresh), and
the skew-tolerance window on staleness.
"""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from aiyagari_hark_tpu.serve.lease import (
    CASServer,
    LoopbackCASBackend,
    MemoryCASBackend,
    SharedDirBackend,
    key_from_hex,
    make_backend,
)
from aiyagari_hark_tpu.serve.replicated import ReplicatedCASBackend
from aiyagari_hark_tpu.utils.fingerprint import fingerprint_hex

# ISSUE 18 adds the quorum client over three loopback replicas: the SAME
# election semantics must hold when "the backend" is a majority vote.
BACKENDS = ("shared-dir", "memory-cas", "loopback-cas", "replicated-cas")


class _Harness:
    """One backend under test plus the substrate-specific aging hook
    (``backdate``) the conformance suite needs to drive staleness
    deterministically."""

    def __init__(self, backend, backdate, cleanup=()):
        self.backend = backend
        self.backdate = backdate
        self._cleanup = list(cleanup)

    def close(self):
        self.backend.close()
        for fn in self._cleanup:
            fn()


def _make_harness(kind, tmp_path, skew_tolerance_s=0.0):
    if kind == "shared-dir":
        root = str(tmp_path / "leases")
        os.makedirs(root, exist_ok=True)
        b = SharedDirBackend(root, skew_tolerance_s=skew_tolerance_s)

        def backdate(key, dt_s):
            path = b._path(key)
            t = os.path.getmtime(path) - float(dt_s)
            os.utime(path, (t, t))

        return _Harness(b, backdate)
    if kind == "memory-cas":
        b = MemoryCASBackend(skew_tolerance_s=skew_tolerance_s)
        return _Harness(b, b.backdate)
    if kind == "loopback-cas":
        srv = CASServer(skew_tolerance_s=skew_tolerance_s).start()
        b = LoopbackCASBackend(srv.address)
        return _Harness(b, b.backdate, cleanup=[srv.stop])
    if kind == "replicated-cas":
        srvs = [CASServer(skew_tolerance_s=skew_tolerance_s).start()
                for _ in range(3)]
        b = ReplicatedCASBackend([s.address for s in srvs],
                                 skew_tolerance_s=skew_tolerance_s)
        return _Harness(b, b.backdate, cleanup=[s.stop for s in srvs])
    raise AssertionError(kind)


@pytest.fixture(params=BACKENDS)
def harness(request, tmp_path):
    h = _make_harness(request.param, tmp_path)
    yield h
    h.close()


KEY = -7_654_321_987            # negative: exercises the two's-complement
#                                 hex spelling round trip on disk names


def test_election_exactly_once_sequential(harness):
    b = harness.backend
    assert b.try_acquire(KEY, "a") is True
    assert b.try_acquire(KEY, "b") is False     # held by a peer
    assert b.try_acquire(KEY, "a") is False     # not reentrant either
    assert b.owner_of(KEY) == "a"
    assert b.list_keys() == [KEY]
    assert b.release(KEY, owner="a") is True
    assert b.list_keys() == []


def test_election_exactly_once_threaded_burst(harness):
    b = harness.backend
    wins = []
    barrier = threading.Barrier(8)

    def racer(i):
        barrier.wait()
        if b.try_acquire(KEY, f"w{i}"):
            wins.append(i)

    threads = [threading.Thread(target=racer, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1, f"election won {len(wins)} times: {wins}"
    assert b.owner_of(KEY) == f"w{wins[0]}"


def test_heartbeat_keeps_live_winner(harness):
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    harness.backdate(KEY, 30.0)
    assert b.age_s(KEY) >= 29.0                  # visibly stale pre-beat
    assert b.heartbeat(KEY, "a") is True         # the owner is alive
    assert b.age_s(KEY) < 5.0                    # stamp refreshed
    assert b.break_stale(KEY, ttl_s=10.0) is False
    assert b.owner_of(KEY) == "a"


def test_ttl_reclaims_dead_owner_then_reelection(harness):
    b = harness.backend
    assert b.try_acquire(KEY, "dead")
    assert b.break_stale(KEY, ttl_s=10.0) is False   # fresh: refused
    harness.backdate(KEY, 30.0)
    assert b.break_stale(KEY, ttl_s=10.0) is True    # stale: reclaimed
    assert b.list_keys() == []
    assert b.try_acquire(KEY, "heir") is True        # re-election works
    assert b.owner_of(KEY) == "heir"


def test_reclaim_vs_heartbeat_race(harness):
    # A reclaimer that OBSERVED staleness but whose delete lands after
    # the owner's beat must be refused: acquire, age past the TTL (the
    # reclaimer's staleness read), then beat — the subsequent reclaim
    # attempt finds a refreshed lease and backs off.
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    harness.backdate(KEY, 30.0)
    assert b.age_s(KEY) > 10.0          # the reclaimer's staleness read
    assert b.heartbeat(KEY, "a") is True
    assert b.break_stale(KEY, ttl_s=10.0) is False
    assert b.owner_of(KEY) == "a"


def test_release_and_heartbeat_are_owner_checked(harness):
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    assert b.release(KEY, owner="b") is False    # not yours to drop
    assert b.heartbeat(KEY, "b") is False        # you don't hold this
    assert b.owner_of(KEY) == "a"
    assert b.release(KEY, owner="a") is True
    # ownerless release is unconditional (the audit/GC spelling)
    assert b.try_acquire(KEY, "c")
    assert b.release(KEY) is True


def test_late_release_after_reclaim_spares_the_heir(harness):
    # The stalled-winner bug the owner check exists for: a's lease is
    # TTL-reclaimed and re-acquired by b; when a finally wakes, its
    # release must NOT delete b's fresh lease and its heartbeat must
    # report the loss.
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    harness.backdate(KEY, 30.0)
    assert b.break_stale(KEY, ttl_s=10.0) is True
    assert b.try_acquire(KEY, "b") is True
    assert b.release(KEY, owner="a") is False
    assert b.heartbeat(KEY, "a") is False
    assert b.owner_of(KEY) == "b"


def test_backwards_clock_reads_fresh(harness):
    # ISSUE 16 satellite regression: a wall clock stepped BACKWARD must
    # clamp to age zero, never poison staleness.
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    past = time.time() - 3600.0
    assert b.age_s(KEY, now=past) == 0.0
    assert b.break_stale(KEY, ttl_s=0.001, now=past) is False
    assert b.owner_of(KEY) == "a"


@pytest.mark.parametrize("kind", BACKENDS)
def test_skew_tolerance_widens_staleness(kind, tmp_path):
    # A reclaimer running AHEAD by less than the tolerance cannot steal
    # from a live owner; beyond ttl + tolerance the reclaim goes through.
    h = _make_harness(kind, tmp_path, skew_tolerance_s=5.0)
    try:
        b = h.backend
        assert b.try_acquire(KEY, "a")
        now = time.time()
        assert b.break_stale(KEY, ttl_s=1.0, now=now + 1.0 + 3.0) is False
        assert b.owner_of(KEY) == "a"
        assert b.break_stale(KEY, ttl_s=1.0, now=now + 1.0 + 60.0) is True
        assert b.list_keys() == []
    finally:
        h.close()


def test_absent_key_semantics(harness):
    b = harness.backend
    assert b.age_s(KEY) is None
    assert b.owner_of(KEY) is None
    assert b.release(KEY) is False
    assert b.heartbeat(KEY, "a") is False
    assert b.break_stale(KEY, ttl_s=0.0) is False
    assert b.list_keys() == []


def test_lease_names_share_the_disk_spelling(harness):
    b = harness.backend
    assert b.try_acquire(KEY, "a")
    names = [os.path.basename(n) for n in b.lease_names()]
    assert names == [f"lease_{fingerprint_hex(KEY)}.lease"]
    assert key_from_hex(fingerprint_hex(KEY)) == KEY


def test_shared_dir_sweeps_unpadded_legacy_spelling(tmp_path):
    """Pre-trait sweeps globbed the directory and acted on the paths
    found there; a lease file with an UNPADDED hex stem (e.g. a
    handcrafted ``lease_feedbeef.lease``) must still be listed, read,
    and TTL-broken even though canonical claims write the zero-padded
    form."""
    from aiyagari_hark_tpu.utils.checkpoint import acquire_lease

    b = make_backend("dir", root=str(tmp_path))
    legacy = os.path.join(str(tmp_path), "lease_feedbeef.lease")
    assert acquire_lease(legacy, owner="dead")
    key = key_from_hex("feedbeef")
    assert b.list_keys() == [key]
    assert b.owner_of(key) == "dead"
    old = time.time() - 10.0
    os.utime(legacy, (old, old))
    assert b.break_stale(key, ttl_s=1.0) is True
    assert not os.path.exists(legacy)
    assert b.list_keys() == []


def test_make_backend_spellings(tmp_path):
    assert isinstance(make_backend("dir", root=str(tmp_path)),
                      SharedDirBackend)
    assert isinstance(make_backend("memory"), MemoryCASBackend)
    cas = make_backend("cas:127.0.0.1:1")
    assert isinstance(cas, LoopbackCASBackend)
    cas.close()
    rep = make_backend("replicated:127.0.0.1:1,127.0.0.1:2,127.0.0.1:3")
    assert isinstance(rep, ReplicatedCASBackend)
    rep.close()
    with pytest.raises(ValueError):
        make_backend("dir")               # needs a root
    with pytest.raises(ValueError):
        make_backend("zookeeper:foo")
    with pytest.raises(ValueError):
        make_backend("replicated:127.0.0.1:1,127.0.0.1:2")  # even count


# -- two REAL processes race the same election ------------------------------
#
# O_EXCL (shared-dir) and the server-side lock (loopback CAS) are only
# meaningful against another PROCESS; the in-memory backend is excluded
# by construction (it is a dict).

_CHILD = r"""
import json, sys
from aiyagari_hark_tpu.serve.lease import make_backend

spec, root, owner, n_keys, out = sys.argv[1:6]
b = make_backend(spec, root=root if root != "-" else None)
wins = [k for k in range(1, int(n_keys) + 1) if b.try_acquire(k, owner)]
b.close()
with open(out, "w") as f:   # atomic-ok: test child's private result file
    json.dump({"wins": wins}, f)
"""


def _race_two_processes(spec, root, tmp_path, n_keys=24):
    outs = [str(tmp_path / f"race{i}.json") for i in range(2)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, spec, root, f"w{i}",
         str(n_keys), outs[i]],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True) for i in range(2)]
    results = []
    for i, p in enumerate(procs):
        _, err = p.communicate(timeout=120)
        assert p.returncode == 0, f"child {i} failed:\n{err}"
        with open(outs[i]) as f:
            results.append(json.load(f)["wins"])
    all_wins = results[0] + results[1]
    # exactly-once fleet-wide: every key elected one winner, no key two
    assert len(all_wins) == len(set(all_wins)), (
        f"duplicate election wins across processes: {sorted(all_wins)}")
    assert sorted(all_wins) == list(range(1, n_keys + 1))


def test_two_process_claim_race_shared_dir(tmp_path):
    root = str(tmp_path / "leases")
    os.makedirs(root)
    _race_two_processes("dir", root, tmp_path)
    assert sorted(SharedDirBackend(root).list_keys()) == list(range(1, 25))


def test_two_process_claim_race_loopback_cas(tmp_path):
    with CASServer() as srv:
        _race_two_processes(f"cas:{srv.address}", "-", tmp_path)
        assert sorted(srv.backend.list_keys()) == list(range(1, 25))


def test_two_process_claim_race_replicated_cas(tmp_path):
    # Exactly-once must survive TWO quorum clients in different
    # interpreters racing the same 3-replica set: the decision point is
    # each replica's server-side conditional write, majority-voted.
    srvs = [CASServer().start() for _ in range(3)]
    try:
        spec = "replicated:" + ",".join(s.address for s in srvs)
        _race_two_processes(spec, "-", tmp_path)
        for s in srvs:
            assert sorted(s.backend.list_keys()) == list(range(1, 25))
    finally:
        for s in srvs:
            s.stop()
