"""Aux subsystems: pytree checkpointing, KS resume, phase timers, JSONL
records (SURVEY.md §5)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_model import AFuncParams
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from aiyagari_hark_tpu.utils.checkpoint import (
    load_ks_checkpoint,
    load_pytree,
    save_ks_checkpoint,
    save_pytree,
)
from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig
from aiyagari_hark_tpu.utils.timing import (
    PhaseTimer,
    read_records_jsonl,
    write_records_jsonl,
)

SMALL_AGENT = AgentConfig(labor_states=4, agent_count=64, a_count=12)
SMALL_ECON = EconomyConfig(labor_states=4, act_T=200, t_discard=40,
                           verbose=False, tolerance=0.05)


def test_pytree_roundtrip(tmp_path):
    tree = {"a": np.arange(6).reshape(2, 3),
            "b": (jnp.ones(4), np.float64(2.5))}
    p = str(tmp_path / "t.npz")
    save_pytree(p, tree)
    out = load_pytree(p, tree)
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"][0], np.ones(4))
    assert float(out["b"][1]) == 2.5


def test_pytree_wrong_template_raises(tmp_path):
    p = str(tmp_path / "t.npz")
    save_pytree(p, {"a": np.ones(3)})
    with pytest.raises(ValueError):
        load_pytree(p, {"a": np.ones(3), "b": np.ones(3)})


def test_select_backend_cpu_oracle():
    """backend='cpu' resolves to the x64 CPU oracle coherently (platform +
    dtype + x64 in one call); bad names are rejected."""
    from aiyagari_hark_tpu.utils.backend import select_backend

    info = select_backend("cpu")
    assert info.name == "cpu" and info.x64 and info.is_oracle
    assert jnp.zeros((), dtype=info.dtype).dtype == jnp.float64
    with pytest.raises(ValueError):
        select_backend("gpu")


def test_pytree_same_leaf_count_different_structure_raises(tmp_path):
    """Same leaf count but different treedef must be rejected (the stored
    treedef guard), not silently reinterpreted."""
    p = str(tmp_path / "t.npz")
    save_pytree(p, {"a": np.ones(3), "b": np.ones(2)})
    with pytest.raises(ValueError, match="structure"):
        load_pytree(p, {"x": np.ones(3), "y": np.ones(2)})
    with pytest.raises(ValueError, match="structure"):
        load_pytree(p, (np.ones(3), np.ones(2)))


def test_ks_checkpoint_roundtrip(tmp_path):
    p = str(tmp_path / "ks.npz")
    afunc = AFuncParams(intercept=jnp.array([0.1, 0.2]),
                        slope=jnp.array([0.9, 1.1]))
    save_ks_checkpoint(p, afunc, iteration=7, seed=3, converged=False)
    ck = load_ks_checkpoint(p)
    np.testing.assert_allclose(ck.intercept, [0.1, 0.2])
    np.testing.assert_allclose(ck.slope, [0.9, 1.1])
    assert int(ck.iteration) == 7 and int(ck.seed) == 3
    assert not bool(ck.converged)


@pytest.mark.slow
def test_ks_solve_resumes_from_checkpoint(tmp_path):
    p = str(tmp_path / "ks.npz")
    timer = PhaseTimer()
    sol1 = solve_ks_economy(SMALL_AGENT, SMALL_ECON, seed=0,
                            checkpoint_path=p, timer=timer)
    n1 = len(sol1.records)
    assert n1 >= 1
    assert timer.seconds["solve"] > 0 and timer.seconds["simulate"] > 0
    # converged checkpoint -> idempotent reload: rule untouched, zero
    # iterations, policy/history rebuilt
    sol2 = solve_ks_economy(SMALL_AGENT, SMALL_ECON, seed=0,
                            checkpoint_path=p)
    assert len(sol2.records) == 0 and sol2.converged
    np.testing.assert_array_equal(np.asarray(sol2.afunc.slope),
                                  np.asarray(sol1.afunc.slope))
    assert sol2.history is not None and sol2.final_panel is not None
    # a mismatched seed or config must refuse to clobber the checkpoint
    with pytest.raises(ValueError, match="different run"):
        solve_ks_economy(SMALL_AGENT, SMALL_ECON, seed=1, checkpoint_path=p)
    with pytest.raises(ValueError, match="different run"):
        solve_ks_economy(SMALL_AGENT,
                         SMALL_ECON.replace(damping_fac=0.25),
                         seed=0, checkpoint_path=p)
    assert int(load_ks_checkpoint(p).seed) == 0   # file untouched


def test_phase_timer_summary():
    t = PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b"):
        pass
    assert t.counts["a"] == 2 and t.counts["b"] == 1
    assert "total" in t.summary()


def test_records_jsonl_roundtrip(tmp_path):
    from aiyagari_hark_tpu.models.ks_solver import KSIterationRecord
    p = str(tmp_path / "r.jsonl")
    recs = [KSIterationRecord(iteration=0, intercept=[0.1, 0.2],
                              slope=[1.0, 1.0], r_squared=[0.9, 0.9],
                              distance=0.5, egm_iters=100, wall_seconds=1.0),
            {"iteration": 1, "distance": 0.1}]
    write_records_jsonl(p, recs)
    out = read_records_jsonl(p)
    assert out[0]["iteration"] == 0 and out[0]["slope"] == [1.0, 1.0]
    assert out[0]["egm_status"] == 0          # solver-health code rides along
    assert out[1]["distance"] == 0.1
    with open(p) as f:
        import dataclasses
        assert (len(json.loads(f.readline()))
                == len(dataclasses.fields(KSIterationRecord)))


def test_checked_call_catches_nan_inside_while_loop():
    """checkify float checks see NaN born inside a lax.while_loop, where
    jax_debug_nans cannot instrument (SURVEY.md §5 sanitizers row)."""
    import jax
    from aiyagari_hark_tpu.utils.debug import checked_call

    def bad_fixed_point(x0):
        def body(state):
            x, it = state
            # log of a negative number appears at iteration 3
            return jnp.log(x - 1.5), it + 1

        def cond(state):
            return state[1] < 5

        return jax.lax.while_loop(cond, body, (x0, 0))[0]

    with pytest.raises(Exception, match="nan"):
        checked_call(bad_fixed_point, jnp.asarray(2.0))
    # clean computations pass through unchanged
    out = checked_call(lambda a: jnp.sqrt(a) * 2.0, jnp.asarray(4.0))
    assert float(out) == pytest.approx(4.0)


def test_validators_catch_corruption():
    from aiyagari_hark_tpu.models.household import (
        build_simple_model,
        initial_distribution,
        initial_policy,
    )
    from aiyagari_hark_tpu.utils.debug import (
        validate_distribution,
        validate_policy,
    )

    m = build_simple_model(labor_states=3, a_count=8, dist_count=16)
    pol = initial_policy(m)
    validate_policy(pol)                      # sane -> passes
    bad = pol._replace(c_knots=pol.c_knots.at[0, 3].set(jnp.nan))
    with pytest.raises(ValueError, match="non-finite"):
        validate_policy(bad)
    crossed = pol._replace(m_knots=pol.m_knots.at[0, 3].set(0.0))
    with pytest.raises(ValueError, match="non-increasing"):
        validate_policy(crossed)

    dist = initial_distribution(m)
    validate_distribution(dist)
    with pytest.raises(ValueError, match="mass"):
        validate_distribution(dist * 0.5)


def test_legacy_ks_checkpoint_migrates(tmp_path):
    """Checkpoints written by earlier layouts (no secant memory / no
    last_distance / no last_residual) load with conservative defaults
    instead of hard-failing — resumability of long runs is this module's
    purpose.

    The legacy files are written under a NamedTuple literally named
    ``KSCheckpoint`` (what the old code actually wrote) — NOT the loader's
    private alias classes.  The stored treedef embeds the writer's class
    name, so writing with the alias masked a dead migration path where
    every tier raised on the name before structure was considered
    (round-3 review finding)."""
    import collections

    import numpy as np

    from aiyagari_hark_tpu.utils.checkpoint import (
        load_ks_checkpoint,
        save_pytree,
    )

    # round-1 layout: 6 fields, class named KSCheckpoint
    V1 = collections.namedtuple(
        "KSCheckpoint",
        "intercept slope iteration seed converged fingerprint")
    p = str(tmp_path / "legacy_v1.npz")
    save_pytree(p, V1(
        intercept=np.asarray([0.1, 0.2]), slope=np.asarray([1.0, 1.1]),
        iteration=np.asarray(7, np.int64), seed=np.asarray(3, np.int64),
        converged=np.asarray(True), fingerprint=np.asarray(42, np.int64)))
    ck = load_ks_checkpoint(p)
    np.testing.assert_array_equal(ck.intercept, [0.1, 0.2])
    assert int(ck.iteration) == 7 and bool(ck.converged)
    assert np.isnan(ck.secant).all()
    # migrated "converged" must NOT short-circuit a resume: inf distance
    # fails any tolerance check
    assert np.isinf(ck.last_distance)
    assert np.isinf(ck.last_residual)

    # round-2 layout: 8 fields (secant + last_distance), same class name
    V3 = collections.namedtuple(
        "KSCheckpoint",
        "intercept slope iteration seed converged fingerprint secant "
        "last_distance")
    p3 = str(tmp_path / "legacy_v3.npz")
    save_pytree(p3, V3(
        intercept=np.asarray([0.3, 0.4]), slope=np.asarray([0.0, 0.0]),
        iteration=np.asarray(9, np.int64), seed=np.asarray(0, np.int64),
        converged=np.asarray(True), fingerprint=np.asarray(7, np.int64),
        secant=np.asarray([1.0, 2.0, 3.0, 4.0]),
        last_distance=np.asarray(1e-4)))
    ck3 = load_ks_checkpoint(p3)
    np.testing.assert_array_equal(ck3.secant, [1.0, 2.0, 3.0, 4.0])
    assert float(ck3.last_distance) == 1e-4
    # the residual is unknown for a round-2 file: +inf forces a pinned
    # resume to re-certify instead of trusting a stale convergence claim
    assert np.isinf(ck3.last_residual)


def test_pytree_strict_rejects_isomorphic_namedtuple(tmp_path):
    """Exact treedef matching is the DEFAULT again: a structurally
    isomorphic but differently named NamedTuple must not silently load
    (the name-erasing comparison is scoped to migration loaders via
    strict=False — round-3 review)."""
    from typing import NamedTuple

    class WriterState(NamedTuple):
        a: np.ndarray
        b: np.ndarray

    class OtherState(NamedTuple):
        a: np.ndarray
        b: np.ndarray

    p = str(tmp_path / "nt.npz")
    save_pytree(p, WriterState(a=np.ones(3), b=np.zeros(2)))
    with pytest.raises(ValueError):
        load_pytree(p, OtherState(a=np.ones(3), b=np.zeros(2)))
    out = load_pytree(p, OtherState(a=np.ones(3), b=np.zeros(2)),
                      strict=False)
    np.testing.assert_allclose(out.a, np.ones(3))
