"""SolutionStore semantics (ISSUE 4 satellite): LRU eviction order,
content-address inequality, donor nomination, and the disk tier's
reload-without-resolve contract."""

import numpy as np
import pytest

from aiyagari_hark_tpu.serve import (
    EquilibriumService,
    SolutionStore,
    make_query,
    make_solution,
)
from aiyagari_hark_tpu.solver_health import CONVERGED, NONFINITE

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)
GROUP = 7


def entry(key, cell=(3.0, 0.6, 0.2), r_star=0.035, group=GROUP,
          status=CONVERGED):
    packed = np.asarray([r_star, 5.0, 0.9, 11.0, 500.0, 4000.0,
                         float(status), 0.0, 4500.0, 0.0])
    return make_solution(cell, packed, group, key)


# ---------------------------------------------------------------------------
# LRU semantics.
# ---------------------------------------------------------------------------

def test_lru_eviction_order():
    store = SolutionStore(capacity=2)
    store.put(entry(1))
    store.put(entry(2))
    assert store.mem_keys() == [1, 2]
    assert store.get(1) is not None           # promote 1 -> MRU
    assert store.mem_keys() == [2, 1]
    store.put(entry(3))                       # evicts 2 (the LRU), not 1
    assert store.mem_keys() == [1, 3]
    assert store.get(2) is None               # memory-only: forgotten
    assert store.get(1) is not None
    assert store.known() == 2


def test_put_refresh_moves_to_mru():
    store = SolutionStore(capacity=2)
    store.put(entry(1))
    store.put(entry(2))
    store.put(entry(1, r_star=0.04))          # refresh promotes
    store.put(entry(3))
    assert store.mem_keys() == [1, 3]
    assert float(store.get(1).packed[0]) == 0.04


def test_put_refuses_failed_status():
    store = SolutionStore(capacity=4)
    with pytest.raises(ValueError):
        store.put(entry(9, status=NONFINITE))


# ---------------------------------------------------------------------------
# Content addressing: any differing input -> a different key.
# ---------------------------------------------------------------------------

def test_solution_key_differs_when_any_input_differs():
    base = make_query(3.0, 0.6, **KW)
    variants = [
        make_query(3.0001, 0.6, **KW),                    # cell: crra
        make_query(3.0, 0.61, **KW),                      # cell: rho
        make_query(3.0, 0.6, labor_sd=0.25, **KW),        # cell: sd
        make_query(3.0, 0.6, dtype=np.float32, **KW),     # dtype
        make_query(3.0, 0.6, **{**KW, "a_count": 11}),    # grid size
        make_query(3.0, 0.6, **{**KW, "r_tol": 2e-4}),    # tolerance
        make_query(3.0, 0.6, **{**KW, "max_bisect": 17}),
        make_query(3.0, 0.6, **KW, dist_method="dense"),  # extra kwarg
    ]
    keys = {q.key() for q in variants}
    assert base.key() not in keys
    assert len(keys) == len(variants)         # all pairwise distinct


def test_solution_key_canonicalization():
    """Keyword order and the dtype=None alias must NOT split the address
    (the dtype aliasing bug class of ISSUE 2, at the cache-key layer)."""
    a = make_query(3.0, 0.6, a_count=10, r_tol=1e-4)
    b = make_query(3.0, 0.6, r_tol=1e-4, a_count=10)
    assert a.key() == b.key() and a.group() == b.group()
    import jax.numpy as jnp

    c = make_query(3.0, 0.6, dtype=jnp.float64, a_count=10, r_tol=1e-4)
    assert a.key() == c.key()                  # None == explicit default


# ---------------------------------------------------------------------------
# Donor nomination.
# ---------------------------------------------------------------------------

def test_nominate_picks_true_nearest_neighbor():
    store = SolutionStore(capacity=8)
    store.put(entry(1, cell=(3.0, 0.60, 0.2), r_star=0.035))
    store.put(entry(2, cell=(3.0, 0.90, 0.2), r_star=0.030))
    store.put(entry(3, cell=(1.0, 0.65, 0.2), r_star=0.040))
    width, r_tol = 0.12, 1e-4
    nom = store.nominate((3.0, 0.65, 0.2), GROUP, width, r_tol)
    # normalized distances: 1 -> 0.056, 2 -> 0.278, 3 -> 0.5: key 1 wins
    assert nom.donor_key == 1
    assert nom.target == 0.035
    # margin covers the spread to the SECOND-nearest donor (key 2)
    assert nom.margin >= abs(0.035 - 0.030)


def test_nominate_scopes_to_group_and_cutoff():
    store = SolutionStore(capacity=8, donor_cutoff=0.5)
    store.put(entry(1, cell=(3.0, 0.6, 0.2), group=GROUP))
    assert store.nominate((3.0, 0.65, 0.2), GROUP + 1, 0.12, 1e-4) is None
    # inside the cutoff: nominated; across the lattice: declined
    assert store.nominate((3.0, 0.65, 0.2), GROUP, 0.12, 1e-4) is not None
    assert store.nominate((1.0, 0.0, 0.2), GROUP, 0.12, 1e-4) is None


def test_nominate_single_donor_margin_floor():
    store = SolutionStore(capacity=8)
    store.put(entry(1, cell=(3.0, 0.6, 0.2), r_star=0.035))
    width, r_tol = 0.12, 1e-4
    nom = store.nominate((3.0, 0.65, 0.2), GROUP, width, r_tol)
    assert nom.margin == pytest.approx(max(0.08 * width, 64.0 * r_tol))


# ---------------------------------------------------------------------------
# Disk tier: restart reuses entries, corrupt files degrade.
# ---------------------------------------------------------------------------

def test_disk_tier_survives_restart(tmp_path):
    d = str(tmp_path / "solstore")
    store = SolutionStore(capacity=4, disk_path=d)
    store.put(entry(11, cell=(1.0, 0.3, 0.2), r_star=0.041))
    store.put(entry(12, cell=(3.0, 0.6, 0.2), r_star=0.035))

    reborn = SolutionStore(capacity=4, disk_path=d)
    assert reborn.known() == 2
    assert len(reborn) == 0                   # index only; memory cold
    sol = reborn.get(11)
    assert sol is not None
    assert np.array_equal(np.asarray(sol.packed),
                          np.asarray(store.get(11).packed))
    assert len(reborn) == 1                   # promoted on get
    # donors survive the restart too
    assert reborn.nominate((1.0, 0.35, 0.2), GROUP, 0.12,
                           1e-4).donor_key == 11


def test_disk_tier_eviction_keeps_entry_addressable(tmp_path):
    store = SolutionStore(capacity=1, disk_path=str(tmp_path / "s"))
    store.put(entry(1, cell=(1.0, 0.3, 0.2)))
    store.put(entry(2, cell=(3.0, 0.6, 0.2)))   # evicts 1 from memory
    assert store.mem_keys() == [2]
    assert store.known() == 2
    assert store.get(1) is not None             # reloaded from disk


def test_corrupt_disk_entry_skipped(tmp_path):
    d = tmp_path / "s"
    store = SolutionStore(capacity=4, disk_path=str(d))
    store.put(entry(1, cell=(1.0, 0.3, 0.2)))
    (d / "sol_00000000deadbeef.npz").write_bytes(b"not an npz")
    with pytest.warns(UserWarning, match="unreadable"):
        reborn = SolutionStore(capacity=4, disk_path=str(d))
    assert reborn.known() == 1


def test_service_disk_reload_serves_without_resolving(tmp_path):
    """The end-to-end restart contract: a second service process over the
    same disk path serves the stored calibration as an exact hit — zero
    cold solves, zero XLA compiles."""
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    d = str(tmp_path / "serve_store")
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             disk_path=d, ladder=(1, 2, 4))
    first = svc.query(3.0, 0.6, **KW)
    assert first.path == "cold"
    svc.close()

    svc2 = EquilibriumService(start_worker=False, max_batch=4,
                              disk_path=d, ladder=(1, 2, 4))
    with CompileCounter() as c:
        again = svc2.query(3.0, 0.6, **KW)
    assert again.path == "hit"
    assert c.compile_events == 0 and c.cache_misses == 0
    assert (again.r_star, again.capital, again.labor) == (
        first.r_star, first.capital, first.labor)
    assert svc2.metrics.snapshot()["serve_cold_rate"] == 0.0
    svc2.close()
