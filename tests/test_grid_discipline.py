"""Grid-discipline lint (ISSUE 12 satellite): solver hot paths build
grids through the GridPolicy seam, never the raw builders directly."""

import importlib.util
import os

import pytest

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
spec = importlib.util.spec_from_file_location(
    "check_grid_discipline",
    os.path.join(repo, "scripts", "check_grid_discipline.py"))
lint = importlib.util.module_from_spec(spec)
spec.loader.exec_module(lint)


def test_grid_discipline_lint_is_clean():
    findings = lint.scan()
    assert not findings, "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_grid_discipline_covers_the_hot_dirs():
    rels = {os.path.relpath(p, repo).replace(os.sep, "/")
            for p in lint.scan_targets()}
    # the seam's consumers are in scope ...
    assert "aiyagari_hark_tpu/models/household.py" in rels
    assert "aiyagari_hark_tpu/scenarios/huggett.py" in rels
    assert "aiyagari_hark_tpu/verify/certificate.py" in rels
    assert any(r.startswith("aiyagari_hark_tpu/serve/") for r in rels)
    # ... the seam itself is not (ops/ IS the resolution layer)
    assert not any(r.startswith("aiyagari_hark_tpu/ops/") for r in rels)


@pytest.mark.parametrize("src,n_expected", [
    # a bare call is a finding
    ("from ..ops.grids import make_asset_grid\n"
     "g = make_asset_grid(0.001, 50.0, 32)\n", 2),
    # attribute-form call too
    ("from ..ops import grids\n"
     "g = grids.make_grid_exp_mult(0.001, 50.0, 32, 2)\n", 1),
    # a waived line is not
    ("from ..ops.grids import make_asset_grid  # grid-ok: fixture\n"
     "g = make_asset_grid(0.001, 50.0, 32)  # grid-ok: fixture\n", 0),
    # the seam call is never banned
    ("from ..ops.grids import build_asset_grids\n"
     "a, d, k = build_asset_grids('compact', 0.001, 50.0, 32, 2, 500)\n",
     0),
])
def test_grid_discipline_fixtures(src, n_expected):
    findings = lint.scan_source(src, "aiyagari_hark_tpu/models/x.py")
    assert len(findings) == n_expected, findings


def test_grid_discipline_script_exit_codes(tmp_path):
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable,
         os.path.join(repo, "scripts", "check_grid_discipline.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout
