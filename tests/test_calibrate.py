"""Calibration utilities (models/calibrate.py).

Oracle: self-consistency — calibrating to the equilibrium quantity of a
KNOWN parameter must recover that parameter (round trip through two
independent directions of the equilibrium map)."""

import numpy as np
import pytest

from aiyagari_hark_tpu.models.calibrate import (
    calibrate_discount_factor,
    calibrate_labor_weight,
)
from aiyagari_hark_tpu.models.equilibrium import solve_equilibrium_lean
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.models.labor import (
    build_labor_model,
    solve_labor_equilibrium,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')

ALPHA, DELTA, CRRA = 0.36, 0.08, 2.0


@pytest.fixture(scope="module")
def model():
    # a_count 24 / dist_count 100 (was 30/120): every assertion in this
    # module is a self-consistency round trip THROUGH the same model, so
    # the grid resolution does not affect assertion strength — only the
    # per-GE-evaluation cost (VERDICT r3 weak-item 5)
    return build_simple_model(labor_states=3, a_count=24, dist_count=100)


def test_discount_factor_round_trip(model):
    beta_true = 0.955
    r_target = solve_equilibrium_lean(model, beta_true, CRRA, ALPHA,
                                      DELTA).r_star
    # bracket encodes the known answer's neighborhood; the recovery
    # assertion (atol 2e-5) is what verifies the inversion
    cal = calibrate_discount_factor(model, r_target, CRRA, ALPHA, DELTA,
                                    beta_lo=0.945, beta_hi=0.965)
    assert bool(cal.converged)
    np.testing.assert_allclose(float(cal.value), beta_true, atol=2e-5)
    np.testing.assert_allclose(float(cal.achieved), float(r_target),
                               atol=1e-5)


def test_unreachable_target_flags_nonconvergence(model):
    """A target outside the bracket's attainable range must come back
    converged=False (the bisection collapses onto an endpoint)."""
    cal = calibrate_discount_factor(model, 0.20, CRRA, ALPHA, DELTA)
    assert not bool(cal.converged)


def test_discount_factor_hits_paper_target(model):
    """Calibrate to Aiyagari's paper value r* = 4.09% and verify the
    achieved equilibrium return."""
    cal = calibrate_discount_factor(model, 0.0409, CRRA, ALPHA, DELTA)
    assert 0.90 < float(cal.value) < 0.995
    np.testing.assert_allclose(float(cal.achieved), 0.0409, atol=1e-5)


def test_gini_histogram_matches_numpy_oracle(model):
    from aiyagari_hark_tpu.models.calibrate import gini_histogram
    from aiyagari_hark_tpu.utils.stats import gini

    rng = np.random.default_rng(0)
    w = rng.random(model.dist_grid.shape[0])
    g_jax = float(gini_histogram(model.dist_grid,
                                 __import__("jax").numpy.asarray(w)))
    g_np = gini(np.asarray(model.dist_grid), w)
    np.testing.assert_allclose(g_jax, g_np, atol=1e-12)


def test_beta_spread_round_trip(model):
    """Carroll et al. workflow: the Gini produced by a KNOWN spread must
    be recovered by the calibration (through a full heterogeneous
    equilibrium per evaluation)."""
    from aiyagari_hark_tpu.models.calibrate import (
        calibrate_beta_spread,
        gini_histogram,
    )
    from aiyagari_hark_tpu.models.heterogeneity import (
        population_distribution,
        solve_heterogeneous_equilibrium,
        uniform_beta_types,
    )
    import jax.numpy as jnp

    spread_true = 0.012
    eq = solve_heterogeneous_equilibrium(
        model, uniform_beta_types(0.96, spread_true, 4), jnp.ones(4),
        CRRA, ALPHA, DELTA)
    g_target = float(gini_histogram(
        model.dist_grid, population_distribution(eq).sum(axis=1)))
    cal = calibrate_beta_spread(model, g_target, 0.96, CRRA, ALPHA,
                                DELTA, spread_tol=1e-4,
                                spread_lo=0.008, spread_hi=0.016)
    assert bool(cal.converged)
    np.testing.assert_allclose(float(cal.value), spread_true, atol=5e-4)
    np.testing.assert_allclose(float(cal.achieved), g_target, atol=5e-3)


def test_spread_fit_closes_the_scf_lorenz_gap():
    """The cstwMPC estimation against the REAL SCF Lorenz curve (vendored
    from the reference's committed figure): the reference's headline
    failure is that the homogeneous model misses the SCF badly (distance
    0.9714, 'too little inequality'); fitting the beta-dist spread closes
    most of the gap.  Measured at this coarse config: homogeneous 0.862
    -> fitted 0.145 at spread* = 0.013 in 11 GE evaluations."""
    from aiyagari_hark_tpu.models.calibrate import calibrate_spread_to_lorenz

    model = build_simple_model(labor_states=4, labor_ar=0.3, labor_sd=0.2,
                               a_count=20, dist_count=100)
    # bracket (0.002, 0.026) strictly CONTAINS the interior-optimum
    # interval asserted below, so landing inside (0.004, 0.022) still
    # discriminates an interior optimum from bracket-endpoint collapse
    fit = calibrate_spread_to_lorenz(model, 0.96, 1.0, 0.36, 0.08,
                                     n_types=4, spread_tol=1.5e-3,
                                     spread_lo=0.002, spread_hi=0.026)
    assert fit.distance_homogeneous > 0.8      # the reference's gap
    assert fit.distance < 0.25                 # mostly closed
    assert 0.004 < fit.spread < 0.022          # interior optimum
    assert fit.distance < fit.distance_homogeneous / 3.0
    assert 0.0 < fit.r_star_pct < 4.1667       # equilibrium stays sane


def test_labor_weight_round_trip():
    lmodel = build_labor_model(frisch=1.0, labor_weight=12.0,
                               labor_states=3, a_count=24, dist_count=80)
    hours_target = solve_labor_equilibrium(lmodel, 0.96, CRRA, ALPHA,
                                           DELTA).mean_hours
    cal = calibrate_labor_weight(lmodel, hours_target, 0.96, CRRA,
                                 ALPHA, DELTA, chi_lo=8.0, chi_hi=18.0)
    np.testing.assert_allclose(float(cal.value), 12.0, rtol=2e-3)
    np.testing.assert_allclose(float(cal.achieved), float(hours_target),
                               rtol=1e-4)


def test_gini_negative_total_wealth_is_nan():
    """Negative aggregate wealth (borrow_limit < 0 economies) is outside
    the Gini's domain: report NaN, not a floor-scaled garbage magnitude
    (round-3 review); zero total wealth keeps its documented Gini-1."""
    import jax.numpy as jnp

    from aiyagari_hark_tpu.models.calibrate import gini_histogram

    grid = jnp.asarray([-2.0, -1.0, 0.5])
    masses = jnp.asarray([0.5, 0.3, 0.2])       # total wealth < 0
    assert bool(jnp.isnan(gini_histogram(grid, masses)))
    zero = gini_histogram(jnp.asarray([0.0, 0.0]), jnp.asarray([0.5, 0.5]))
    np.testing.assert_allclose(float(zero), 1.0)
