"""Preemption-tolerant run layer (ISSUE 3): durable sweep resume,
graceful-shutdown signal handling, transient-fault retry with backoff.

The load-bearing assertion is the KILL-AND-RESUME acceptance test: a
12-cell CPU sweep interrupted after bucket k — by an injected SIGTERM and
by an injected transient fault, separately — resumes via ``resume_path``
and produces a ``SweepResult`` bit-identical to the uninterrupted run,
including statuses, iteration counters, and a quarantined cell.  The
companion contract: a transient fault at call k is retried on the
deterministic backoff schedule, while a solver-health ``NONFINITE`` is
NEVER retried by this layer (that is the PR 1 quarantine ladder's job).
"""

import os
import signal

import numpy as np
import pytest

from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
from aiyagari_hark_tpu.solver_health import (
    INTERRUPTED,
    SolverDivergenceError,
    is_failure,
)
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.resilience import (
    InjectedTransientError,
    Interrupted,
    RetryPolicy,
    TransientInjector,
    classify_transient,
    clear_interrupt,
    interrupt_requested,
    preemption_guard,
    raise_if_interrupted,
    request_interrupt,
    retry_transient,
)

# Reduced-size solver config shared with tests/test_sweep_scheduler.py —
# same lru/jit cache keys, so this module rides the same warm compiles.
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
# Quarantined cell: stall-injected so it exits MAX_ITER, is quarantined,
# and walks one ladder rung — the resume must replay its retry outcome.
FAULT = {"cell": 2, "at_iter": 2, "mode": "stall"}
TWELVE = SweepConfig(schedule="balanced", n_buckets=3)
SMALL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                    schedule="balanced", n_buckets=2)


def spy_policy(**kw):
    """A RetryPolicy whose sleeps are captured, not paid."""
    slept = []
    kw.setdefault("base_delay", 0.25)
    policy = RetryPolicy(sleep=slept.append, **kw)
    return policy, slept


def assert_sweep_identical(a, b):
    """Bit-identity over every per-cell field of two SweepResults —
    values, NaN masks, statuses, iteration counters, retry counts, and
    the scheduler's bucket/work-model bookkeeping."""
    for f in ("r_star_pct", "saving_rate_pct", "capital", "excess",
              "predicted_work"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f)),
                              equal_nan=True), f
    for f in ("bisect_iters", "egm_iters", "dist_iters", "status",
              "retries", "bucket"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


# -- retry_transient: policy, classifier, injection -------------------------


def test_retry_policy_deterministic_backoff_schedule():
    p = RetryPolicy(max_attempts=5, base_delay=0.5, multiplier=2.0,
                    max_delay=3.0)
    assert [p.delay(i) for i in range(5)] == [0.5, 1.0, 2.0, 3.0, 3.0]


def test_retry_transient_retries_injected_fault_per_schedule():
    policy, slept = spy_policy(max_attempts=3)
    inject = TransientInjector(at_call=0, times=2)
    calls = []
    out = retry_transient(lambda: calls.append(1) or "ok", policy,
                          inject=inject, label="unit")
    assert out == "ok"
    assert len(calls) == 1                 # two injected raises, then work
    assert slept == [policy.delay(0), policy.delay(1)]


def test_retry_transient_exhaustion_reraises():
    policy, slept = spy_policy(max_attempts=2)
    inject = TransientInjector(at_call=0, times=5)
    with pytest.raises(InjectedTransientError):
        retry_transient(lambda: "never", policy, inject=inject)
    assert slept == [policy.delay(0)]      # one backoff between 2 attempts


def test_retry_transient_never_retries_nonfinite():
    """The hard rule: numeric divergence is the quarantine ladder's job —
    the transient layer must re-raise SolverDivergenceError immediately,
    with zero sleeps."""
    policy, slept = spy_policy(max_attempts=5)

    def diverge():
        raise SolverDivergenceError("NONFINITE in the inner loop",
                                    status=3)

    with pytest.raises(SolverDivergenceError):
        retry_transient(diverge, policy)
    assert slept == []


def test_retry_transient_non_transient_raises_immediately():
    policy, slept = spy_policy(max_attempts=5)
    with pytest.raises(ValueError):
        retry_transient(lambda: (_ for _ in ()).throw(
            ValueError("bad argument")), policy)
    assert slept == []


def test_classify_transient_rules():
    assert classify_transient(InjectedTransientError("x"))
    assert classify_transient(ConnectionError("peer reset"))
    assert classify_transient(RuntimeError("UNAVAILABLE: socket closed"))
    assert classify_transient(RuntimeError("DEADLINE_EXCEEDED: 60s"))
    assert classify_transient(RuntimeError("RESOURCE_EXHAUSTED: quota"))
    assert not classify_transient(SolverDivergenceError("nan", status=3))
    assert not classify_transient(ValueError("UNAVAILABLE"))  # type wins
    assert not classify_transient(RuntimeError("assertion failed"))
    assert not classify_transient(KeyboardInterrupt())
    assert not classify_transient(Interrupted("shutdown"))
    # gRPC codes are matched SHOUTED — prose must not trip the retry
    assert not classify_transient(RuntimeError("operation aborted by user"))
    # device OOM is RESOURCE_EXHAUSTED but deterministic: never replayed
    assert not classify_transient(RuntimeError(
        "RESOURCE_EXHAUSTED: Attempting to allocate 12.5G in HBM"))


# -- preemption_guard: signals, escalation, teardown ------------------------


def test_preemption_guard_turns_sigterm_into_typed_interrupt():
    with preemption_guard():
        assert not interrupt_requested()
        os.kill(os.getpid(), signal.SIGTERM)   # a real signal, as in prod
        assert interrupt_requested()
        with pytest.raises(Interrupted) as ei:
            raise_if_interrupted("unit loop", resume_path="/tmp/x.npz",
                                 progress={"step": 3})
        assert ei.value.signum == signal.SIGTERM
        assert ei.value.status == INTERRUPTED
        assert is_failure(ei.value.status)     # uncertified exit
        assert ei.value.resume_path == "/tmp/x.npz"
        assert ei.value.progress == {"step": 3}
    # guard exit clears the flag and restores the default disposition
    assert not interrupt_requested()
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_preemption_guard_second_signal_escalates():
    with preemption_guard():
        os.kill(os.getpid(), signal.SIGTERM)
        with pytest.raises(KeyboardInterrupt, match="second SIGTERM"):
            os.kill(os.getpid(), signal.SIGTERM)
            # the handler fires at the next bytecode boundary; touching
            # the flag guarantees we cross one
            interrupt_requested()


def test_preemption_guard_teardown_gcs_orphaned_tmp(tmp_path):
    """A hard kill between an atomic writer's write and rename strands a
    tmp sibling; guard teardown sweeps it (age-gated, logged)."""
    target = str(tmp_path / "ledger.npz")
    stale = str(tmp_path / "tmpabc123.npz.tmp")
    with open(stale, "w") as f:
        f.write("stranded")
    with pytest.warns(UserWarning, match="orphaned checkpoint tmp"):
        with preemption_guard(gc_paths=(target,), max_tmp_age_s=0.0):
            pass
    assert not os.path.exists(stale)


def test_calibration_polls_at_evaluation_boundaries():
    """calibrate_spread_to_lorenz honors a shutdown request at its next
    evaluation boundary — before launching another multi-second GE solve."""
    from aiyagari_hark_tpu.models.calibrate import calibrate_spread_to_lorenz
    from aiyagari_hark_tpu.models.household import build_simple_model

    model = build_simple_model(labor_states=3, a_count=8, dist_count=16)
    try:
        request_interrupt()
        with pytest.raises(Interrupted) as ei:
            calibrate_spread_to_lorenz(model, 0.95, 2.0, 0.36, 0.08,
                                       n_types=2)
    finally:
        clear_interrupt()
    assert ei.value.progress == {"evaluations": 0}   # nothing was solved


def test_nested_guard_and_flag_injection():
    with preemption_guard():
        with preemption_guard():
            request_interrupt()
            assert interrupt_requested()
        # inner exit must NOT clear the flag (outer guard still winding
        # down), only the outermost does
        assert interrupt_requested()
    assert not interrupt_requested()


# -- the kill-and-resume acceptance (12-cell CPU sweep) ---------------------


@pytest.fixture(scope="module")
def uninterrupted():
    """The reference run: full 12-cell lattice, bucketed schedule, one
    stall-injected cell that the quarantine ladder retries."""
    res = run_table2_sweep(TWELVE, inject_fault=FAULT, max_retries=1, **KW)
    assert int(res.retries[FAULT["cell"]]) >= 1     # quarantine really ran
    return res


def test_sigterm_after_bucket_k_resumes_bit_identical(tmp_path,
                                                      uninterrupted):
    """Injected SIGTERM after bucket 0: the sweep flushes its ledger and
    raises the typed Interrupted; a rerun with the same resume_path skips
    the solved bucket and reassembles bit-identically."""
    ledger = str(tmp_path / "sweep_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted) as ei:
            run_table2_sweep(
                TWELVE, inject_fault=FAULT, max_retries=1,
                resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "signal"}, **KW)
    assert ei.value.signum == signal.SIGTERM
    assert ei.value.resume_path == ledger
    assert ei.value.progress["completed_buckets"] == 1
    assert os.path.exists(ledger)          # valid state flushed pre-raise
    with np.load(ledger) as raw:           # some, not all, cells solved
        n_leaves = len([k for k in raw.files if k.startswith("leaf_")])
    assert n_leaves == 8                   # the SweepLedger layout
    #                                        (+checksums, ISSUE 6)

    resumed = run_table2_sweep(TWELVE, inject_fault=FAULT, max_retries=1,
                               resume_path=ledger, **KW)
    assert not os.path.exists(ledger)      # completed runs clean up
    assert_sweep_identical(resumed, uninterrupted)


def test_transient_fault_mid_sweep_resumes_bit_identical(tmp_path,
                                                         uninterrupted):
    """A transient fault at call k=1 (the second bucket launch) that
    exhausts the retry budget escapes; the ledger holds bucket 0 and the
    rerun resumes bit-identically.  The backoff between the two attempts
    follows the policy's deterministic schedule."""
    ledger = str(tmp_path / "sweep_ledger.npz")
    policy, slept = spy_policy(max_attempts=2)
    with pytest.raises(InjectedTransientError):
        run_table2_sweep(
            TWELVE, inject_fault=FAULT, max_retries=1, resume_path=ledger,
            retry=policy, inject_transient={"at_call": 1, "times": 2},
            **KW)
    assert slept == [policy.delay(0)]      # retried once, per schedule
    assert os.path.exists(ledger)

    resumed = run_table2_sweep(TWELVE, inject_fault=FAULT, max_retries=1,
                               resume_path=ledger, **KW)
    assert not os.path.exists(ledger)
    assert_sweep_identical(resumed, uninterrupted)


def test_transient_fault_retried_in_place_same_bits():
    """A transient fault that does NOT exhaust the budget is absorbed: the
    launch replays (pure program, same bits) and the sweep completes in
    one call, identical to a fault-free run."""
    clean = run_table2_sweep(SMALL, **KW)
    policy, slept = spy_policy(max_attempts=3)
    with pytest.warns(UserWarning, match="transient fault in sweep"):
        faulted = run_table2_sweep(
            SMALL, retry=policy,
            inject_transient={"at_call": 0, "times": 1}, **KW)
    assert slept == [policy.delay(0)]
    assert_sweep_identical(faulted, clean)


def test_nonfinite_goes_to_quarantine_not_transient_retry():
    """An injected NONFINITE is handled by the solver-health quarantine
    ladder; the transient-retry layer must consume ZERO attempts on it."""
    policy, slept = spy_policy(max_attempts=5)
    res = run_table2_sweep(SMALL, inject_fault={"cell": 1, "at_iter": 1,
                                                "mode": "nan"},
                           max_retries=1, retry=policy, **KW)
    assert slept == []                     # no transient retries fired
    assert int(res.retries[1]) >= 1        # the quarantine ladder did run
    # retries never re-inject, so the ladder recovers the cell cleanly
    assert not is_failure(int(res.status[1]))
    assert np.isfinite(res.r_star_pct[1])


def test_stale_ledger_warns_and_recomputes(tmp_path):
    """A ledger written under a different configuration must degrade
    loudly to a fresh run — never silently satisfy the launches."""
    ledger = str(tmp_path / "sweep_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_table2_sweep(
                SMALL, resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "flag"}, **KW)
    assert os.path.exists(ledger)
    other = dict(KW)
    other["r_tol"] = 2e-5                  # different solver config
    with pytest.warns(UserWarning, match="different run"):
        res = run_table2_sweep(SMALL, resume_path=ledger, **other)
    assert np.isfinite(res.r_star_pct).all()
    assert not os.path.exists(ledger)


def test_mesh_shape_refuses_resume_and_recomputes(tmp_path):
    """Mesh-shape resume safety (ISSUE 11): the lane-axis device count is
    hashed into the ledger fingerprint, so a ledger written under an
    8-device mesh loaded under 1 device — and vice versa — warns typed
    ("different run") and recomputes, with the final SweepResult
    bit-identical to an uninterrupted run either way (the per-lane bits
    are mesh-independent; only the resume GEOMETRY is not)."""
    from aiyagari_hark_tpu.parallel.mesh import cells_mesh

    mesh = cells_mesh()
    clean = run_table2_sweep(SMALL, **KW)               # 1-device ref
    clean_8 = run_table2_sweep(SMALL, mesh=mesh, **KW)  # 8-device ref

    # written on 8 devices, resumed on 1: refuse + recompute, and the
    # recomputed run is bit-identical to the uninterrupted 1-DEVICE run
    # (same launch geometry — the comparison the fingerprint protects)
    ledger = str(tmp_path / "mesh8_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_table2_sweep(
                SMALL, mesh=mesh, resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "flag"}, **KW)
    assert os.path.exists(ledger)
    with pytest.warns(UserWarning, match="different run"):
        res_1 = run_table2_sweep(SMALL, resume_path=ledger, **KW)
    assert not os.path.exists(ledger)
    assert_sweep_identical(res_1, clean)

    # written on 1 device, resumed on 8: refuse + recompute, bit-identical
    # to the uninterrupted 8-device run
    ledger = str(tmp_path / "mesh1_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_table2_sweep(
                SMALL, resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "flag"}, **KW)
    assert os.path.exists(ledger)
    with pytest.warns(UserWarning, match="different run"):
        res_8 = run_table2_sweep(SMALL, mesh=mesh, resume_path=ledger,
                                 **KW)
    assert not os.path.exists(ledger)
    assert_sweep_identical(res_8, clean_8)

    # the SAME mesh shape DOES resume: no recompute warning, and the
    # restored-bucket result is bit-identical to the uninterrupted
    # 8-device run
    ledger = str(tmp_path / "mesh_same_ledger.npz")
    with preemption_guard():
        with pytest.raises(Interrupted):
            run_table2_sweep(
                SMALL, mesh=mesh, resume_path=ledger,
                inject_preempt={"after_bucket": 0, "mode": "flag"}, **KW)
    resumed = run_table2_sweep(SMALL, mesh=mesh, resume_path=ledger, **KW)
    assert not os.path.exists(ledger)
    assert_sweep_identical(resumed, clean_8)

    # per-lane solver bits are mesh-independent up to the documented
    # aggregate-contraction carve-out: r*/status/counters bitwise across
    # the two geometries
    assert np.array_equal(clean.r_star_pct, clean_8.r_star_pct)
    assert np.array_equal(clean.status, clean_8.status)
    assert np.array_equal(clean.egm_iters, clean_8.egm_iters)


def test_locked_schedule_resumes_through_quarantine(tmp_path):
    """The lock-step path is one "bucket" to the ledger: a preemption
    between the launch and the quarantine rungs resumes without
    relaunching the batch, bit-identically."""
    cfg = SMALL.replace(schedule="locked")
    clean = run_table2_sweep(cfg, inject_fault=FAULT, max_retries=1, **KW)
    ledger = str(tmp_path / "locked_ledger.npz")
    try:
        request_interrupt()                # flag set before the call:
        with pytest.raises(Interrupted):   # honored right after the launch
            run_table2_sweep(cfg, inject_fault=FAULT, max_retries=1,
                             resume_path=ledger, **KW)
    finally:
        clear_interrupt()
    assert os.path.exists(ledger)
    resumed = run_table2_sweep(cfg, inject_fault=FAULT, max_retries=1,
                               resume_path=ledger, **KW)
    for f in ("r_star_pct", "capital"):
        assert np.array_equal(np.asarray(getattr(resumed, f)),
                              np.asarray(getattr(clean, f)),
                              equal_nan=True), f
    for f in ("bisect_iters", "egm_iters", "dist_iters", "status",
              "retries"):
        assert np.array_equal(np.asarray(getattr(resumed, f)),
                              np.asarray(getattr(clean, f))), f
