"""utils.fingerprint (ISSUE 4 satellite): the consolidated cache-key
vocabulary — roundtrip determinism, sensitivity, and the no-drift
contract between the subsystems that share keys."""

import numpy as np
import pytest

from aiyagari_hark_tpu.utils.fingerprint import (
    config_fingerprint,
    hashable_kwargs,
    ledger_fingerprint,
    solution_fingerprint,
    work_fingerprint,
)

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)


def test_config_fingerprint_deterministic_and_sensitive():
    a = np.arange(6, dtype=np.float64)
    assert config_fingerprint(a, "x", 3) == config_fingerprint(a, "x", 3)
    assert config_fingerprint(a, "x", 3) != config_fingerprint(a, "x", 4)
    assert config_fingerprint(a) != config_fingerprint(a.astype(np.float32))
    assert config_fingerprint(None) != config_fingerprint("none-ish")


def test_hashable_kwargs_canonical_order_and_sequences():
    items = hashable_kwargs(dict(b=2, a=1))
    assert items == (("a", 1), ("b", 2))
    assert hashable_kwargs(dict(a=1, b=2)) == items
    seq = hashable_kwargs(dict(g=[1.0, 2.0]))
    assert seq == (("g", (1.0, 2.0)),)
    with pytest.raises(TypeError):
        hashable_kwargs(dict(bad={"not": "hashable"}))
    with pytest.raises(TypeError):
        hashable_kwargs(dict(bad=np.zeros((2, 2))))


def test_work_fingerprint_roundtrip_and_dtype_alias():
    items = hashable_kwargs(KW)
    fp = work_fingerprint(items, np.float64)
    assert work_fingerprint(items, None) == fp        # np.dtype(None)=f64
    assert work_fingerprint(items, "float64") == fp
    assert work_fingerprint(items, np.float32) != fp
    other = hashable_kwargs({**KW, "a_count": 11})
    assert work_fingerprint(other, np.float64) != fp


def test_work_fingerprint_matches_sweep_sidecar_key():
    """The no-drift contract: the sweep's sidecar key and the serving
    store's group key are the SAME function — a sidecar written by the
    batch path must address the same solver group serving reads."""
    from aiyagari_hark_tpu.parallel import sweep

    assert sweep._work_fingerprint is work_fingerprint
    assert sweep._hashable_kwargs is hashable_kwargs
    from aiyagari_hark_tpu.utils import checkpoint

    assert checkpoint.config_fingerprint is config_fingerprint


def test_solution_fingerprint_covers_cell_and_config():
    items = hashable_kwargs(KW)
    fp = solution_fingerprint(3.0, 0.6, 0.2, items, np.float64)
    assert solution_fingerprint(3.0, 0.6, 0.2, items, np.float64) == fp
    assert solution_fingerprint(3.0, 0.6, 0.2, items, None) == fp
    distinct = {
        solution_fingerprint(3.1, 0.6, 0.2, items, np.float64),
        solution_fingerprint(3.0, 0.7, 0.2, items, np.float64),
        solution_fingerprint(3.0, 0.6, 0.3, items, np.float64),
        solution_fingerprint(3.0, 0.6, 0.2, items, np.float32),
        solution_fingerprint(3.0, 0.6, 0.2,
                             hashable_kwargs({**KW, "r_tol": 2e-4}),
                             np.float64),
    }
    assert fp not in distinct and len(distinct) == 5


def test_precision_policy_in_group_keys():
    """ISSUE 5 satellite: the precision policy is part of every cache key
    (cross-policy inequality) while the EXPLICIT default spelling hashes
    identically to the implicit one (no-drift pin) — sidecar predictions,
    ledgers, and store entries can neither mix policies nor split on a
    no-op spelling."""
    items = hashable_kwargs(KW)
    # no-drift: explicit "reference" == absent, at every key level
    assert hashable_kwargs({**KW, "precision": "reference"}) == items
    assert (work_fingerprint(
        hashable_kwargs({**KW, "precision": "reference"}), np.float64)
        == work_fingerprint(items, np.float64))
    # cross-policy inequality
    mixed = hashable_kwargs({**KW, "precision": "mixed"})
    fast = hashable_kwargs({**KW, "precision": "fast"})
    assert mixed != items and fast != items and mixed != fast
    keys = {work_fingerprint(it, np.float64) for it in (items, mixed, fast)}
    assert len(keys) == 3
    sols = {solution_fingerprint(3.0, 0.6, 0.2, it, np.float64)
            for it in (items, mixed, fast)}
    assert len(sols) == 3
    # an unknown policy fails loudly before it can alias a real one
    with pytest.raises(ValueError):
        hashable_kwargs({**KW, "precision": "bf16"})


def test_ledger_fingerprint_covers_row_layout():
    """A resume ledger written under a different packed-row layout must
    never fingerprint-match (the pre-widening ledger would feed
    wrong-shaped rows into a restarted sweep).  The layout now arrives
    as the SCENARIO's ``RowSchema.fields`` (ISSUE 9)."""
    from aiyagari_hark_tpu.utils.config import PACKED_ROW_FIELDS

    cells = np.asarray([[1.0, 0.3, 0.2]])
    args = dict(cells=cells,
                kwargs_items=hashable_kwargs(KW), dtype=np.float64,
                schedule="locked", n_buckets=0, warm_brackets=False,
                warm_margin=0.0, fault_mode=None, fault_iters=None,
                max_retries=3, quarantine=True, sidecar=None)
    base = ledger_fingerprint(**args, row_fields=PACKED_ROW_FIELDS)
    # None resolves the registered scenario's schema — same key
    assert ledger_fingerprint(**args) == base
    # the pre-PR-5 7-field layout must never match
    assert ledger_fingerprint(
        **args, row_fields=PACKED_ROW_FIELDS[:7]) != base


def test_ledger_fingerprint_sensitivity():
    cells = np.asarray([[1.0, 0.3, 0.2], [3.0, 0.6, 0.2]])
    items = hashable_kwargs(KW)

    def fp(**over):
        kw = dict(cells=cells, kwargs_items=items,
                  dtype=np.float64, schedule="balanced", n_buckets=0,
                  warm_brackets=False, warm_margin=0.0, fault_mode=None,
                  fault_iters=None, max_retries=3, quarantine=True,
                  sidecar=None)
        kw.update(over)
        return ledger_fingerprint(**kw)

    base = fp()
    assert fp() == base
    assert fp(schedule="locked") != base
    assert fp(warm_brackets=True) != base
    assert fp(cells=cells + 1e-6) != base              # perturb included
    assert fp(fault_iters=np.asarray([0, -1])) != base
    # scenario identity keys the ledger too (ISSUE 9): the same cells
    # and kwargs under another model family can never resume each other
    assert fp(scenario="huggett") != base
    # the sidecar's CONTENT is part of the key (a swapped sidecar between
    # interrupt and resume must invalidate the ledger)
    from aiyagari_hark_tpu.utils.checkpoint import SweepSidecar

    side = SweepSidecar(
        cells=np.asarray([[1.0, 0.3, 0.2]]), r_star=np.asarray([0.04]),
        bisect_iters=np.asarray([11]), egm_iters=np.asarray([500]),
        dist_iters=np.asarray([4000]), descent_steps=np.asarray([0]),
        polish_steps=np.asarray([4500]), status=np.asarray([0]),
        fingerprint=np.asarray(1, np.int64))
    with_side = fp(sidecar=side)
    assert with_side != base
    side2 = side._replace(r_star=np.asarray([0.05]))
    assert fp(sidecar=side2) != with_side
