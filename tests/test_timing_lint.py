"""check_timing_discipline lint (ISSUE 10 satellite): ad-hoc
``time.perf_counter()``/``time.time()`` calls in the hot modules
(``parallel/``, ``serve/``, ``obs/``, ``models/``) must flow through a
``Tracer`` span, ``utils.timing.PhaseTimer``, or
``utils.timing.stopwatch()`` — or carry an explicit ``# timing-ok``
waiver.  Run in tier-1 so a raw clock pair cannot regress in, with
fixture tests proving the lint fires on the patterns it guards."""

import importlib.util
import os


def _load_lint():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_timing_discipline",
        os.path.join(repo, "scripts", "check_timing_discipline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_timing_lint_is_clean():
    """The hot modules contain no unwaived raw clock calls — failing
    here, not in code review."""
    mod, repo = _load_lint()
    findings = mod.scan(repo)
    assert findings == [], "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_timing_lint_covers_hot_modules():
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    for required in ("aiyagari_hark_tpu/parallel/sweep.py",
                     "aiyagari_hark_tpu/serve/service.py",
                     "aiyagari_hark_tpu/serve/loadgen.py",
                     "aiyagari_hark_tpu/obs/trace.py",
                     "aiyagari_hark_tpu/models/ks_solver.py"):
        assert required in rels, required
    # utils/ is deliberately OUT of scope: utils/timing.py is the
    # blessed substrate the rule routes callers through
    assert not any(r.startswith("aiyagari_hark_tpu/utils/")
                   for r in rels)


def test_lint_fires_on_raw_clock_calls():
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "import time\n"
        "t0 = time.perf_counter()\n"
        "work()\n"
        "wall = time.time() - t0\n", "fixture.py")
    assert [line for _, line, _ in findings] == [2, 4]
    assert "stopwatch" in findings[0][2]


def test_lint_fires_on_monotonic_walls_and_accepts_clock_injection():
    """The ISSUE 11 extension: a bare ``time.monotonic()`` CALL next to a
    (sharded) launch is an ad-hoc wall — finding; passing the clock as
    an injectable default (``clock=time.monotonic``) is the blessed
    plumbing pattern — clean; a waived real-time backstop is clean."""
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "import time\n"
        "t0 = time.monotonic()\n"
        "launch()\n"
        "wall = time.monotonic() - t0\n"
        "def f(clock=time.monotonic):\n"        # reference, not a call
        "    return clock\n"
        "end = time.monotonic() + t  # timing-ok: wait backstop\n",
        "fixture.py")
    assert [line for _, line, _ in findings] == [2, 4]


def test_lint_accepts_waivers_and_clock_references():
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "import time\n"
        "t0 = time.perf_counter()   # timing-ok: substrate primitive\n"
        "def f(clock=time.perf_counter):\n"     # reference, not a call
        "    return clock\n"
        "g = dict(clock=time.time)\n", "fixture.py")
    assert findings == []


def test_lint_ignores_docstrings_and_comments():
    mod, _ = _load_lint()
    findings = mod.scan_source(
        '"""Prose about time.perf_counter() pairs."""\n'
        "# a comment about time.time() too\n"
        "x = 1\n", "fixture.py")
    assert findings == []
