"""Deterministic load harness (ISSUE 8): seeded open-loop Zipf traffic
on the injected clock — replayable bit-for-bit, every outcome typed,
zero unresolved futures, journal == report.

Runs at several times modeled capacity (``max_batch /
batch_service_s``), so admission control, shedding, degraded answers,
and deadline machinery all genuinely fire on the tiny CPU lattice."""

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import ObsConfig, read_journal
from aiyagari_hark_tpu.serve import (
    AdmissionPolicy,
    LoadSpec,
    generate_arrivals,
    run_load,
)

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)

# 16 distinct solutions over two sd panels; hottest ranks first.  At
# rate=2000 vs capacity 4/0.01 = 400 queries/s this is ~5x overload.
CELLS = tuple((s, r, sd) for sd in (0.2, 0.3)
              for s in (1.0, 3.0) for r in (0.0, 0.3, 0.6, 0.9))
SPEC = LoadSpec(cells=CELLS, model_kwargs=KW, n_queries=80, seed=11,
                rate=2000.0, zipf_s=0.8,
                priority_mix=(0.4, 0.3, 0.3), deadline_frac=0.2,
                deadline_s=0.02, degraded_frac=0.4,
                batch_service_s=0.01, warm_frac=0.25)
POLICY = AdmissionPolicy(max_work=2.5, est_batch_s=0.01,
                         degraded_pressure=0.4, degraded_distance=0.6)

OUTCOME_VOCAB_PREFIXES = ("served:", "reject:", "fail:")


@pytest.fixture(scope="module")
def baseline_report():
    """One canonical gated run, shared by the replay/journal tests (a
    reproducible harness makes the result reusable by construction)."""
    return run_load(SPEC, admission=POLICY)


def test_generate_arrivals_is_seeded_and_mixed():
    a1 = generate_arrivals(SPEC)
    a2 = generate_arrivals(SPEC)
    assert a1 == a2                          # same seed, same trace
    assert a1 != generate_arrivals(SPEC._replace(seed=12))
    assert len(a1) == SPEC.n_queries
    assert all(b.t > a.t for a, b in zip(a1, a1[1:]))   # open loop
    # the Zipf head dominates: rank-0 cell more popular than rank-last
    hits = [a.cell for a in a1]
    assert hits.count(CELLS[0]) > hits.count(CELLS[-1])
    assert {a.priority for a in a1} <= {0, 1, 2}
    assert any(a.deadline is not None for a in a1)
    assert any(a.degraded_ok for a in a1)


def test_load_replay_is_bit_reproducible_with_typed_outcomes(
        baseline_report):
    r1 = baseline_report
    r2 = run_load(SPEC, admission=POLICY)
    # the acceptance triad: replayable, typed, nothing hangs
    assert r1.digest == r2.digest
    assert r1.outcomes == r2.outcomes
    assert r1.unresolved == 0 and r2.unresolved == 0
    assert all(o.startswith(OUTCOME_VOCAB_PREFIXES)
               for o in r1.outcomes)
    # at ~5x capacity the overload machinery genuinely fires...
    overload = sum(n for o, n in r1.counts.items()
                   if not o.startswith("served:"))
    assert overload > 0
    # ...while exact hits keep being served at full saturation
    assert r1.counts.get("served:hit", 0) > 0
    # every arrival is accounted for
    assert sum(r1.counts.values()) == SPEC.n_queries
    # queue pressure was real and recorded
    assert r1.queue_depth_peak >= 2
    assert r1.queue_depth_p99 is not None
    assert r1.snapshot["serve_failures"] == 0   # no bare/untyped errors


def test_load_outcomes_change_with_the_admission_policy(baseline_report):
    """The digest covers admission decisions: a policy change moves the
    outcome sequence (while staying internally reproducible)."""
    r_gated = baseline_report
    r_open = run_load(SPEC, admission=None, max_queue=4096)
    assert r_gated.digest != r_open.digest
    # without admission nothing is rejected — but nothing hangs either
    assert r_open.unresolved == 0
    assert not any(o.startswith("reject:Overloaded")
                   for o in r_open.outcomes)


def test_load_journal_matches_report(tmp_path, baseline_report):
    """Injected == journaled: every shed/reject/degrade the report
    counts appears exactly that many times in the typed event journal."""
    jp = str(tmp_path / "load.jsonl")
    rep = run_load(SPEC, admission=POLICY,
                   obs=ObsConfig(enabled=True, journal_path=jp))
    snap = rep.snapshot
    for etype, count in (
            ("OVERLOADED", snap["serve_overloaded"]),
            ("LOAD_SHED", snap["serve_load_sheds"]),
            ("DEGRADED_ANSWER", rep.counts.get(
                "served:degraded_neighbor", 0)),
            ("CIRCUIT_REJECT", snap["serve_circuit_rejects"])):
        assert len(read_journal(jp, event=etype)) == count, etype
    # submit rejects + seam expirations both land as DEADLINE_EXCEEDED
    n_deadline = (snap["serve_deadline_rejects_submit"]
                  + snap["serve_deadline_expirations"])
    assert len(read_journal(jp, event="DEADLINE_EXCEEDED")) == n_deadline
    # the journal never changes the replay: same digest as unjournaled
    assert rep.digest == baseline_report.digest


def test_load_hit_path_stays_fast_under_saturation():
    """Real-wall exact-hit latency during the overload run: hits are a
    store lookup and must not queue behind the saturated solve path.
    Bounded generously for CI noise — the bench smoke records the
    precise number."""
    rep = run_load(SPEC, admission=POLICY, measure_hit_wall=True)
    assert len(rep.hit_wall_ms) == rep.counts.get("served:hit", 0)
    assert rep.hit_wall_ms, "spec must produce exact hits"
    p50 = float(np.median(rep.hit_wall_ms))
    assert p50 < 50.0                        # µs-class op, ms-class bound


# ---------------------------------------------------------------------------
# Off-lattice arrivals (ISSUE 17): continuous-parameter traffic.
# ---------------------------------------------------------------------------

def test_offlattice_frac_zero_is_bit_identical():
    """The default spec and an explicit frac=0.0 draw the SAME trace as
    the pre-surrogate generator: extra RNG draws happen only when the
    mix is positive, so every committed digest stays valid."""
    assert generate_arrivals(SPEC) \
        == generate_arrivals(SPEC._replace(offlattice_frac=0.0))


def test_offlattice_mix_samples_inside_hull():
    spec = SPEC._replace(offlattice_frac=0.5, n_queries=200)
    a1 = generate_arrivals(spec)
    assert a1 == generate_arrivals(spec)     # still seeded-reproducible
    lattice = set(CELLS)
    off = [a.cell for a in a1 if a.cell not in lattice]
    on = [a.cell for a in a1 if a.cell in lattice]
    assert off and on                        # genuinely a mix
    lo = np.min(np.asarray(CELLS), axis=0)
    hi = np.max(np.asarray(CELLS), axis=0)
    for cell in off:
        assert all(float(l) <= c <= float(h)
                   for c, l, h in zip(cell, lo, hi))
    # a different frac is a different trace (the digest covers it)
    assert a1 != generate_arrivals(spec._replace(offlattice_frac=0.9))
