"""The suite's warm-start tooling itself: registry lookup, the
committed-checkpoint resume-or-degrade helper, and the bench's fine-grid
dense hazard sentinel (all fast — no solves)."""

import os

import pytest

from fixture_configs import (
    SOLVE_KWARGS,
    committed_checkpoint,
    solve_with_committed_checkpoint,
    warm_start,
)


def test_warm_start_registry_and_cold_bypass(monkeypatch):
    ws = warm_start("dist_method")
    assert set(ws) == {"intercept_prev", "slope_prev"}
    assert all(isinstance(v, tuple) and len(v) == 2 for v in ws.values())
    # pinned-mode entries are inside the constant-rule class (slope 0) —
    # the condition under which ks_solver honors them
    assert ws["slope_prev"] == (0.0, 0.0)
    assert warm_start("no_such_fixture") == {}
    monkeypatch.setenv("AIYAGARI_COLD_START", "1")
    assert warm_start("dist_method") == {}


def test_committed_checkpoint_copies_pair(tmp_path, monkeypatch):
    ck = committed_checkpoint("dist_method", tmp_path, tag="x")
    assert ck is not None and ck.endswith("dist_method_x.npz")
    assert os.path.exists(ck) and os.path.exists(ck + ".dist.npz")
    # the committed pair is NEAR-converged, not converged (a converged
    # copy would short-circuit the resume and void the reproducibility
    # assertions that ride on it)
    from aiyagari_hark_tpu.utils.checkpoint import load_ks_checkpoint
    assert not bool(load_ks_checkpoint(ck).converged)
    assert committed_checkpoint("no_such_fixture", tmp_path) is None
    monkeypatch.setenv("AIYAGARI_COLD_START", "1")
    assert committed_checkpoint("dist_method", tmp_path) is None


def test_resume_or_degrade_semantics(tmp_path):
    """Stale fingerprint (CheckpointMismatchError) degrades to a warned
    cold solve; any other failure propagates — a resume-path regression
    must fail tests, not silently cost a cold solve."""
    from aiyagari_hark_tpu.utils.checkpoint import CheckpointMismatchError

    calls = []

    def stale_then_cold(ck):
        calls.append(ck)
        if ck is not None:
            raise CheckpointMismatchError("written by a different run")
        return "cold-result"

    with pytest.warns(UserWarning, match="stale"):
        out = solve_with_committed_checkpoint("dist_method", tmp_path,
                                              stale_then_cold)
    assert out == "cold-result"
    assert calls[0] is not None and calls[1] is None

    def broken(ck):
        raise RuntimeError("resume-path regression")

    with pytest.raises(RuntimeError, match="regression"):
        solve_with_committed_checkpoint("dist_method", tmp_path, broken,
                                        tag="b")


def test_solve_kwargs_cover_every_registry_key():
    """Every registry entry has its solve kwargs defined in the ONE shared
    mapping — the invariant that keeps the refresh script and the tests
    solving the same program."""
    import json

    from fixture_configs import REGISTRY
    with open(REGISTRY) as f:
        for key in json.load(f):
            assert key in SOLVE_KWARGS, key


@pytest.mark.parametrize("name", ["_FINE_SENTINEL", "_WELFARE_SENTINEL"])
def test_bench_hazard_sentinel_lifecycle(tmp_path, monkeypatch, name):
    """Both compile-hazard guards (fine-grid dense, welfare sweep) share
    one lifecycle: write → pending, force-env override, clear → not
    pending, idempotent clear."""
    import bench

    monkeypatch.setattr(bench, "_repo_dir", lambda: str(tmp_path))
    sentinel = getattr(bench, name)
    assert not sentinel.pending()
    sentinel.write()
    assert sentinel.pending()
    # the explicit recovery override re-enables the phase despite the file
    monkeypatch.setenv(sentinel.force_env, "1")
    assert not sentinel.pending()
    monkeypatch.delenv(sentinel.force_env)
    assert sentinel.pending()
    sentinel.clear()
    assert not sentinel.pending()
    sentinel.clear()                      # idempotent on a missing file


def test_bench_model_flops_scatter_vs_dense():
    """The FLOP model's structure: dense distribution steps dominate the
    scatter ones by the D^2/D matvec ratio, and EGM work is identical."""
    import bench

    egm_only = bench._model_flops(10, 0, 32, 7, 500, dense_dist=True)
    assert egm_only == bench._model_flops(10, 0, 32, 7, 500,
                                          dense_dist=False)
    dense = bench._model_flops(0, 10, 32, 7, 500, dense_dist=True)
    scatter = bench._model_flops(0, 10, 32, 7, 500, dense_dist=False)
    assert dense > 50 * scatter
    # per the documented model: dense per-step = 2*N*D^2 + 2*D*N^2
    assert dense == 10 * (2.0 * 7 * 500 ** 2 + 2.0 * 500 * 7 ** 2)
