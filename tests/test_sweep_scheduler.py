"""Sweep scheduler (ISSUE 2): work-balanced bucketing, warm-started
brackets, and the sidecar work model.

The load-bearing assertion is the permutation/bucketing PROPERTY test:
bucketed + work-sorted + warm-bracketed sweeps must return r*, status, and
NaN-masks bit-identical to the single-batch lock-step path — on CPU, across
both Table II panels, including a quarantined (fault-injected) cell — while
cutting total inner-loop work and the post-scheduling straggler ratio.  The
solver configs are reduced-size but the code path is the production one.
"""

import os

import numpy as np
import pytest

from aiyagari_hark_tpu.parallel.mesh import balanced_lane_order, make_mesh
from aiyagari_hark_tpu.parallel.sweep import (
    _canonical_dtype,
    _plan_buckets,
    dyadic_bracket,
    heuristic_cell_work,
    run_table2_sweep,
)
from aiyagari_hark_tpu.utils.checkpoint import (
    CheckpointMismatchError,
    load_sweep_sidecar,
    save_sweep_sidecar,
)
from aiyagari_hark_tpu.utils.config import SweepConfig

# Reduced-size solver config: full scheduling machinery, ~1s/cell on CPU.
# The bitwise warm-vs-locked assertions below are STRONGER than the
# solver's general contract ("bit-identical up to inner-solver noise at
# |excess| ~ solver tolerance") and rely on this config's margins: with
# r_tol=1e-5 the smallest |excess| any evaluated midpoint sees is
# ~slope*5e-6, while f64 inner tolerances (egm 1e-6, dist 1e-11) bound the
# warm/cold excess difference orders of magnitude below that — a sign flip
# (the only way bits can diverge) would need that margin to collapse.
# Shrink r_tol toward the inner tolerances and these become allclose
# assertions, not array_equal.
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
TWO_PANEL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                        labor_sd=(0.2, 0.4))
# Quarantined cell: stall-inject cell 2 so it exits MAX_ITER and (with
# max_retries=0) stays NaN-masked — the property test must cover a failed
# cell's mask and status, not just healthy lanes.
FAULT = {"cell": 2, "at_iter": 2, "mode": "stall"}


# -- pure scheduling helpers (no solves) ------------------------------------

def test_heuristic_work_model_ranks():
    """The cold-start cost model's measured signs: work decreasing in ρ,
    in sd, and (mildly) in σ; always positive."""
    cells = np.asarray([(s, r, sd) for s in (1.0, 3.0, 5.0)
                        for r in (0.0, 0.3, 0.6, 0.9)
                        for sd in (0.2, 0.4)])
    w = heuristic_cell_work(cells)
    assert (w > 0).all()
    for s in (1.0, 5.0):
        for sd in (0.2, 0.4):
            m = (cells[:, 0] == s) & (cells[:, 2] == sd)
            assert (np.diff(w[m]) < 0).all()          # decreasing in rho
    a_panel = heuristic_cell_work(np.asarray([[3.0, 0.3, 0.2]]))
    b_panel = heuristic_cell_work(np.asarray([[3.0, 0.3, 0.4]]))
    assert b_panel < a_panel                          # decreasing in sd


def test_balanced_lane_order_properties():
    """LPT layout: a valid permutation, equal lanes per shard, and a
    per-shard work spread far below the unbalanced contiguous layout's."""
    rng = np.random.default_rng(0)
    work = rng.uniform(1.0, 10.0, size=16)
    perm = balanced_lane_order(work, 4)
    assert sorted(perm.tolist()) == list(range(16))
    shard_tot = work[perm].reshape(4, 4).sum(axis=1)
    naive_tot = np.sort(work)[::-1].reshape(4, 4).sum(axis=1)
    assert shard_tot.max() - shard_tot.min() <= (naive_tot.max()
                                                 - naive_tot.min())
    assert shard_tot.max() <= 1.35 * shard_tot.mean()
    assert (balanced_lane_order(work[:4], 1) == np.arange(4)).all()
    with pytest.raises(ValueError, match="not divisible"):
        balanced_lane_order(work[:6], 4)


@pytest.mark.parametrize("dt", [np.float64, np.float32])
def test_dyadic_bracket_replays_device_arithmetic(dt):
    """The descended endpoints must be bit-exact results of the bisection's
    own halving recursion (mid = 0.5*(lo+hi) in dtype), keep the target
    ball strictly inside, and report the level count."""
    ft = np.dtype(dt).type
    r_lo, r_hi = ft(-0.072), ft(0.0415667)
    lo, hi, lv = dyadic_bracket(r_lo, r_hi, target=0.0299, margin=1e-4,
                                max_levels=40, dtype=dt)
    assert lv > 4
    assert lo <= ft(0.0299 - 1e-4) and ft(0.0299 + 1e-4) <= hi
    # replay the recursion independently: every endpoint must be reachable
    clo, chi = r_lo, r_hi
    for _ in range(lv):
        mid = ft(0.5) * (clo + chi)
        if 0.0299 > mid:
            clo = mid
        else:
            chi = mid
    assert clo == lo and chi == hi
    # a margin wider than the half-bracket never descends
    _, _, lv0 = dyadic_bracket(r_lo, r_hi, target=0.0, margin=0.2,
                               max_levels=40, dtype=dt)
    assert lv0 == 0


def test_plan_buckets_auto_and_padding():
    order = np.arange(12)
    buckets, size = _plan_buckets(order, 0)
    assert len(buckets) == 4 and size == 3          # auto: C/3 capped at 8
    assert np.concatenate(buckets).tolist() == list(range(12))
    buckets, size = _plan_buckets(np.arange(10), 3)
    assert [len(b) for b in buckets] == [4, 4, 2]   # short tail bucket


def test_canonical_dtype_kills_lru_aliasing():
    """dtype=None and the explicit default must map to ONE cache key —
    the two-compiles-for-one-program satellite (x64 is on in tests)."""
    import jax.numpy as jnp

    assert _canonical_dtype(None) == _canonical_dtype(jnp.float64)
    assert _canonical_dtype("float64") == _canonical_dtype(np.float64)
    assert _canonical_dtype(np.float32) == jnp.float32


def test_sidecar_roundtrip_and_fingerprint(tmp_path):
    path = str(tmp_path / "side.npz")
    cells = np.asarray([[1.0, 0.3, 0.2], [5.0, 0.9, 0.4]])
    save_sweep_sidecar(path, cells, [0.041, np.nan], [14, 30], [500, 900],
                       [4000, 9000], [0, 2], fingerprint=123)
    side = load_sweep_sidecar(path, 123)
    assert side.lookup((5.0, 0.9, 0.4)) == 1
    assert side.lookup((1.0, 0.3, 0.2)) == 0
    assert side.lookup((2.0, 0.3, 0.2)) is None
    assert side.total_work().tolist() == [4500, 9900]
    assert np.isnan(side.r_star[1])                  # failed cell: no seed
    with pytest.raises(CheckpointMismatchError, match="fingerprint"):
        load_sweep_sidecar(path, 999)


# -- the property test: scheduled == lock-step, bit for bit -----------------

@pytest.fixture(scope="module")
def sweeps(tmp_path_factory):
    """Lock-step reference (writes the sidecar), then the fully scheduled
    run: work-sorted buckets + warm brackets (sidecar roots for cells the
    lock-step run certified, neighbor seeds for the quarantined cell whose
    sidecar root is NaN), same injected fault."""
    side = str(tmp_path_factory.mktemp("sched") / "side.npz")
    cfg = TWO_PANEL.replace(sidecar_path=side)
    locked = run_table2_sweep(cfg.replace(schedule="locked"),
                              inject_fault=FAULT, max_retries=0, **KW)
    warm = run_table2_sweep(
        cfg.replace(schedule="balanced", n_buckets=2, warm_brackets=True),
        inject_fault=FAULT, max_retries=0, **KW)
    return locked, warm


def test_scheduled_sweep_bit_identical(sweeps):
    locked, warm = sweeps
    assert warm.bucket is not None and locked.bucket is None
    # NaN masks first (array_equal treats NaN != NaN)
    nan_locked = np.isnan(locked.r_star_pct)
    nan_warm = np.isnan(warm.r_star_pct)
    assert (nan_locked == nan_warm).all()
    assert nan_locked[FAULT["cell"]]            # the quarantined cell
    assert np.array_equal(warm.r_star_pct[~nan_warm],
                          locked.r_star_pct[~nan_locked])
    assert np.array_equal(warm.status, locked.status)
    # capital is supply at the LAST EVALUATED point (SweepResult
    # docstring) — the warm path reaches the same final midpoint through
    # a different inner-carry history, so it agrees to solver noise, not
    # bitwise; r*/status/masks above are the bit-identity contract
    assert np.array_equal(np.isnan(warm.capital), np.isnan(locked.capital))
    np.testing.assert_allclose(warm.capital[~nan_warm],
                               locked.capital[~nan_locked], rtol=1e-6)
    # output order is the original cells() order on both paths
    assert np.array_equal(warm.crra, locked.crra)
    assert np.array_equal(warm.labor_ar, locked.labor_ar)
    assert np.array_equal(warm.labor_sd, locked.labor_sd)


def test_scheduled_sweep_cuts_work_and_skew(sweeps):
    locked, warm = sweeps
    # bracket warm-starts must cut total inner-loop work (healthy cells
    # only — the stalled cell burns its trip budget in both runs)
    ok = ~np.isnan(locked.r_star_pct)
    lw = float(locked.total_work()[ok].sum())
    ww = float(warm.total_work()[ok].sum())
    assert ww <= 0.80 * lw, (ww, lw)
    # warm continuation evaluates fewer excess points than lock-step trips
    assert (warm.bisect_iters[ok] < locked.bisect_iters[ok]).all()


def test_twelve_cell_schedule_meets_acceptance(tmp_path):
    """The ISSUE 2 acceptance numbers on the 12-cell CPU sweep: bucketed
    scheduling drops the post-scheduling straggler ratio below 1.6, warm
    brackets cut total inner-loop steps >= 25%, and both stay
    bit-identical to the lock-step reference."""
    side = str(tmp_path / "side12.npz")
    cfg = SweepConfig(sidecar_path=side)       # full 12-cell lattice
    cold = run_table2_sweep(cfg, **KW)         # auto -> balanced, heuristic
    assert cold.bucket is not None
    assert cold.scheduled_iteration_skew() < 1.6
    locked = run_table2_sweep(cfg.replace(schedule="locked",
                                          sidecar_path=None), **KW)
    assert np.array_equal(cold.r_star_pct, locked.r_star_pct)
    assert cold.iteration_skew() == locked.iteration_skew()
    warm = run_table2_sweep(cfg.replace(warm_brackets=True), **KW)
    assert np.array_equal(warm.r_star_pct, locked.r_star_pct)
    assert np.array_equal(warm.status, locked.status)
    reduction = 1.0 - warm.total_work().sum() / locked.total_work().sum()
    assert reduction >= 0.25, f"inner-step reduction only {reduction:.1%}"


def test_scheduled_sweep_under_mesh(tmp_path):
    """Balanced scheduling composes with a sharded mesh: per-device lanes
    are laid out by predicted work and results still come back in cell
    order, equal to the unsharded scheduled run."""
    import jax

    cfg = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.0, 0.9),
                      labor_sd=(0.2, 0.4), schedule="balanced", n_buckets=2)
    mesh = make_mesh(("cells",), (2,), devices=jax.devices()[:2])
    res_m = run_table2_sweep(cfg, mesh=mesh, **KW)
    res_1 = run_table2_sweep(cfg, **KW)
    assert np.array_equal(res_m.r_star_pct, res_1.r_star_pct)
    assert np.array_equal(res_m.status, res_1.status)


def test_sidecar_written_and_reused(tmp_path):
    """The sweep writes its sidecar after solving and a rerun consumes it
    (measured work replaces the heuristic for matched cells)."""
    side = str(tmp_path / "side.npz")
    cfg = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                      schedule="balanced", n_buckets=2, sidecar_path=side)
    first = run_table2_sweep(cfg, **KW)
    assert os.path.exists(side)
    again = run_table2_sweep(cfg, **KW)
    # measured counters are exact for the rerun -> predicted work must
    # match the first run's measured totals for every cell
    assert np.array_equal(np.asarray(again.predicted_work, dtype=np.int64),
                          first.total_work())
    assert np.array_equal(again.r_star_pct, first.r_star_pct)
