"""Tests for the deterministic distribution-iteration simulator
(``simulate_distribution_history``) and its ``sim_method="distribution"``
hookup in the KS outer loop.

The simulator replaces the reference's 350-agent Monte-Carlo panel
(``Aiyagari_Support.py:1161-1162`` + hooks, SURVEY.md §3.3) with an exact
histogram push-forward — the oracle here is the panel simulator itself in the
large-agent limit (MC error ~ N^{-1/2}), plus conservation-law invariants the
histogram operator must satisfy exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_model import (
    AFuncParams,
    build_ks_calibration,
    solve_ks_household,
)
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from aiyagari_hark_tpu.models.simulate import (
    initial_distribution_panel,
    initial_panel,
    make_sim_dist_grid,
    simulate_distribution_history,
    simulate_markov_history,
    simulate_panel,
)
from aiyagari_hark_tpu.utils.config import notebook_run_configs


@pytest.fixture(scope="module")
def cal():
    agent, econ = notebook_run_configs()
    return build_ks_calibration(agent, econ)


@pytest.fixture(scope="module")
def policy(cal):
    # A stationary perceived rule (K' = KSS regardless of M).  The identity
    # rule (slope 1) is NOT usable here: it makes households expect K' = M
    # (~2.6x steady state), the implied policy has an explosive right tail
    # (panel max assets > 2000), and a histogram with any finite top would
    # truncate it.  Under a stationary rule wealth is bounded (reference
    # max 22.05, BASELINE.md), which is the regime the KS loop operates in.
    ss = cal.steady_state
    afunc = AFuncParams(
        intercept=jnp.full((2,), jnp.log(ss.K), dtype=cal.a_grid.dtype),
        slope=jnp.zeros(2, dtype=cal.a_grid.dtype))
    pol, _, _, _ = solve_ks_household(afunc, cal)
    return pol


@pytest.fixture(scope="module")
def mrkv_hist(cal):
    return simulate_markov_history(cal.agg_transition, 0, 300,
                                   jax.random.PRNGKey(1))


def test_initial_distribution_mass_and_mean(cal):
    """The birth lottery conserves mass exactly and places the mean at the
    steady-state capital (the two-point lottery is mean-preserving)."""
    grid = make_sim_dist_grid(cal, 200)
    init = initial_distribution_panel(cal, grid, 0)
    d = np.asarray(init.dist)
    assert d.shape == (200, cal.labor_levels.shape[0], 2)
    np.testing.assert_allclose(d.sum(), 1.0, atol=1e-12)
    mean_a = float((d.sum(axis=(1, 2)) * np.asarray(grid)).sum())
    np.testing.assert_allclose(mean_a, float(cal.steady_state.K), rtol=1e-10)
    # parity mode: UrateB=UrateG=0 -> all mass employed
    np.testing.assert_allclose(d[:, :, 0].sum(), 0.0, atol=1e-12)


def test_distribution_history_conserves_mass(cal, policy, mrkv_hist):
    grid = make_sim_dist_grid(cal, 200)
    hist, final = jax.jit(
        lambda p: simulate_distribution_history(p, cal, mrkv_hist, grid))(
            policy)
    total = float(np.asarray(final.dist).sum())
    np.testing.assert_allclose(total, 1.0, atol=1e-9)
    assert (np.asarray(final.dist) >= -1e-15).all()
    # track_vars contract identical to the panel simulator
    A = np.asarray(hist.A_prev)
    assert A.shape == (300,)
    assert np.isfinite(A).all() and (A > 0).all()
    # degenerate employment (Aiyagari mode): urate identically ~0
    np.testing.assert_allclose(np.asarray(hist.urate), 0.0, atol=1e-12)


def test_distribution_is_deterministic(cal, policy, mrkv_hist):
    """No keys anywhere: two runs are bit-identical (the property the panel
    simulator cannot offer and the 1 bp budget needs, SURVEY.md §7)."""
    grid = make_sim_dist_grid(cal, 150)
    f = jax.jit(lambda p: simulate_distribution_history(
        p, cal, mrkv_hist, grid)[0].A_prev)
    a1, a2 = f(policy), f(policy)
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))


@pytest.mark.slow
def test_distribution_matches_large_panel(cal, policy, mrkv_hist):
    """The histogram push-forward is the N -> infinity limit of the panel:
    with a large agent panel on the same policy and aggregate chain, the
    simulated aggregate-assets path must agree to within MC error."""
    grid = make_sim_dist_grid(cal, 400)
    hist_d, _ = jax.jit(lambda p: simulate_distribution_history(
        p, cal, mrkv_hist, grid))(policy)
    init = initial_panel(cal, 4000, 0, jax.random.PRNGKey(2))
    hist_p, _ = jax.jit(lambda p, k: simulate_panel(
        p, cal, mrkv_hist, init, k))(policy, jax.random.PRNGKey(3))
    a_d = np.asarray(hist_d.A_prev)[100:]
    a_p = np.asarray(hist_p.A_prev)[100:]
    # time-mean of aggregate assets: MC std of the panel mean is well under
    # 1% here; allow 3% for the histogram's finite-grid bias
    np.testing.assert_allclose(a_d.mean(), a_p.mean(), rtol=0.03)
    # the paths themselves co-move (same chain, same policy)
    corr = np.corrcoef(a_d, a_p)[0, 1]
    assert corr > 0.95


@pytest.mark.slow
def test_solve_ks_economy_distribution_method(tmp_path):
    """The deterministic (slope-pinned secant) equilibrium mode: converges,
    reproduces exactly, and cross-validates against the *independent*
    bisection engine — the rational-expectations r* of the shockless
    economy, 4.125% (``tests/test_equilibrium.py`` golden), NOT the
    reference's MC-attenuated 4.178% (see ``solve_ks_economy`` docstring
    on ``dist_pin_slope``)."""
    # Config + committed warm start + near-converged committed checkpoint
    # (this fixture's cost is the carried distribution settling, which an
    # intercept warm start cannot cut): tests/fixture_configs.py.  The
    # resume runs the final iterations and the convergence certification
    # for real; staleness semantics live in
    # solve_with_committed_checkpoint.
    from fixture_configs import (SOLVE_KWARGS, dist_method_configs,
                                 solve_with_committed_checkpoint)
    agent, econ = dist_method_configs()
    kwargs = SOLVE_KWARGS["dist_method"]

    def solve(tag):
        return solve_with_committed_checkpoint(
            "dist_method", tmp_path,
            lambda ck: solve_ks_economy(agent, econ, **kwargs,
                                        checkpoint_path=ck), tag)

    sol = solve("a")
    assert sol.converged
    assert len(sol.records) > 0   # resumed runs really iterate+certify
    # |r* - bisection golden| small: independent-method cross-validation
    # (histogram grid / M-interpolation differences allow a few bp)
    assert abs(sol.equilibrium_r_pct - 4.125) < 0.05
    # pinned rule: slope identically zero
    np.testing.assert_array_equal(np.asarray(sol.afunc.slope), 0.0)
    # final_panel is the histogram state; mass still sums to one
    np.testing.assert_allclose(float(np.asarray(sol.final_panel.dist).sum()),
                               1.0, atol=1e-8)
    # exact reproducibility of the whole outer loop (both runs resume the
    # same committed state from their own tmp copies — identical inputs)
    sol2 = solve("b")
    np.testing.assert_array_equal(np.asarray(sol.afunc.intercept),
                                  np.asarray(sol2.afunc.intercept))


@pytest.mark.slow
def test_initial_condition_fan_and_pooled_regression(cal, policy):
    """``initial_distribution_fan`` stacks mill-consistent starts on a
    leading axis, and ``calc_afunc_update`` pools that axis into one
    regression sample (the deterministic-dithering machinery for measuring
    the unconstrained aggregate map)."""
    from aiyagari_hark_tpu.models.ks_solver import calc_afunc_update
    from aiyagari_hark_tpu.models.simulate import initial_distribution_fan
    from aiyagari_hark_tpu.models.ks_model import AFuncParams as AFP

    grid = make_sim_dist_grid(cal, 150)
    fan = initial_distribution_fan(cal, grid, 0, 5)
    assert fan.dist.shape == (5, 150, cal.labor_levels.shape[0], 2)
    # per-path mass is 1 and initial capital is spread geometrically
    np.testing.assert_allclose(np.asarray(fan.dist).sum(axis=(1, 2, 3)),
                               1.0, atol=1e-12)
    k0 = (np.asarray(fan.dist).sum(axis=(2, 3)) * np.asarray(grid)).sum(1)
    assert k0[0] < k0[2] < k0[4]
    np.testing.assert_allclose(k0[2], float(cal.steady_state.K), rtol=1e-9)
    # prices are milled from each path's own k0, not the steady state's
    assert float(fan.R_now[0]) > float(fan.R_now[4])
    # pooled regression over the fan identifies the transition map: slope
    # is finite, R^2 high (deterministic transients are near log-linear)
    mrkv = simulate_markov_history(cal.agg_transition, 0, 200,
                                   jax.random.PRNGKey(5))
    hist = jax.vmap(lambda i0: simulate_distribution_history(
        policy, cal, mrkv, grid, i0))(fan)[0]
    assert hist.A_prev.shape == (5, 200)
    afunc0 = AFP(intercept=jnp.zeros(2), slope=jnp.ones(2))
    new, rsq = calc_afunc_update(hist, mrkv, afunc0, 25, 0.0)
    assert np.isfinite(np.asarray(new.slope)).all()
    assert (np.asarray(rsq) > 0.95).all()


@pytest.mark.slow
def test_pinned_resume_continues_secant_trajectory(tmp_path):
    """Killing a pinned run and resuming from its checkpoint reproduces the
    uninterrupted trajectory exactly — the secant memory (previous iterate,
    residual, bracket) rides in the checkpoint."""
    agent, econ = notebook_run_configs()
    # max_loops=40: with the fixed-price pinned iteration the convergence
    # criterion includes the fixed-point residual |g|, which at this short
    # act_T decays one carry-over window at a time (near the 1/beta - 1 cap
    # the wealth distribution mixes with time constant ~1/(1 - beta R)
    # periods, several times act_T here)
    econ = econ.replace(act_T=800, t_discard=160, verbose=False,
                        max_loops=40, tolerance=1e-3)
    kwargs = dict(seed=0, sim_method="distribution", dist_count=200)
    full = solve_ks_economy(agent, econ, **kwargs)
    assert full.converged

    ck = str(tmp_path / "pinned.npz")
    part = solve_ks_economy(agent, econ.replace(max_loops=3), **kwargs,
                            checkpoint_path=ck)
    assert not part.converged
    resumed = solve_ks_economy(agent, econ, **kwargs, checkpoint_path=ck)
    assert resumed.converged
    # same trajectory up to EGM-tolerance noise: the secant memory is
    # restored exactly, but the EGM warm-start policy is not checkpointed,
    # so each resumed household solve re-converges from cold within its
    # 1e-6 tolerance — differences stay at that level, far inside the
    # outer tolerance
    np.testing.assert_allclose(np.asarray(resumed.afunc.intercept),
                               np.asarray(full.afunc.intercept), atol=1e-5)
    # and the resumed run did fewer iterations than the full one
    assert len(resumed.records) < len(full.records)
    # resuming a CONVERGED checkpoint with a tighter tolerance must keep
    # iterating (the stored last_distance fails the new tolerance), not
    # short-circuit through the idempotent-reload path
    tighter = solve_ks_economy(agent, econ.replace(tolerance=1e-5),
                               **kwargs, checkpoint_path=ck)
    assert len(tighter.records) > 0
    assert tighter.records[-1].distance < 1e-5


def test_sim_method_rejects_unknown():
    agent, econ = notebook_run_configs()
    with pytest.raises(ValueError, match="sim_method"):
        solve_ks_economy(agent, econ.replace(act_T=40, t_discard=8),
                         sim_method="typo")
