"""Pin the notebook's cell-24 wealth-distribution goldens (VERDICT r2
missing-item 2).

The reference reports simulated-wealth max/mean/std/median =
22.046/5.439/3.697/4.718 and Lorenz-vs-SCF 0.9714 from ONE 350-agent panel
draw (``Aiyagari-HARK.ipynb`` cells 24/27; BASELINE.md).  Those statistics
carry real Monte-Carlo noise, so asserting them honestly needs the
sampling band: ``scripts/wealth_seed_study.py`` measures it over 32 fresh
panel re-simulations of the converged notebook economy (committed as
``tests/data/wealth_seed_study.json``).

Three layers:
 1. the reference goldens sit inside the measured band (fast — data only);
 2. the deterministic histogram engine's stats agree with the panel band
    where the estimators are comparable (fast — data only);
 3. a live re-simulation of study seed 0 reproduces its committed
    per-seed statistics, so the band itself is pinned to current code
    (slow — one full notebook-parity solve).
"""

import json
import os

import numpy as np
import pytest

DATA = os.path.join(os.path.dirname(__file__), "data")


@pytest.fixture(scope="module")
def study():
    with open(os.path.join(DATA, "wealth_seed_study.json")) as f:
        return json.load(f)


def test_reference_goldens_inside_measured_band(study):
    """Every cell-24 golden (and the 0.9714 Lorenz golden) must lie within
    the 32-seed sampling band, modestly widened (|z| < 3 against the seed
    spread — the reference's draw is one more seed)."""
    for key, golden in study["reference_goldens"].items():
        band = study["band"][key]
        z = (golden - band["mean"]) / max(band["std"], 1e-12)
        assert abs(z) < 3.0, (key, golden, band, z)
        # and inside the observed min/max envelope widened by one sd
        assert band["min"] - band["std"] <= golden <= band["max"] + band["std"], (
            key, golden, band)


def test_histogram_engine_agrees_with_panel_band(study):
    """The deterministic histogram engine (fixed-price pinned secant) and
    the Monte-Carlo panel estimate the same distribution: mean/std/median/
    Lorenz of the exact histogram fall inside (a one-sd widening of) the
    panel band.  ``max`` is excluded by design: the histogram resolves
    ergodic tail mass (~3e-4 above wealth 30) that a 350-agent draw
    essentially never samples, so its occupied-support max is not
    comparable to a finite panel's."""
    h = study["histogram_stats"]
    for key in ("mean", "std", "median", "lorenz_vs_scf"):
        band = study["band"][key]
        lo = band["min"] - band["std"]
        hi = band["max"] + band["std"]
        assert lo <= h[key] <= hi, (key, h[key], band)


@pytest.mark.slow
def test_seed_zero_resimulation_reproduces_study(study):
    """Re-run the study's seed-0 panel through current code and require the
    committed per-seed statistics to reproduce — the regression pin that
    makes the committed band meaningful for the current solver/simulator.
    Exact up to the solve's own convergence tolerance (the policy is
    re-solved, not replayed), so tolerances are loose-but-binding."""
    import jax
    import jax.numpy as jnp

    from aiyagari_hark_tpu import (AiyagariEconomy, AiyagariType,
                                   init_aiyagari_agents,
                                   init_aiyagari_economy)
    from aiyagari_hark_tpu.models.simulate import (initial_panel,
                                                   simulate_panel)
    from aiyagari_hark_tpu.utils import stats

    cfg = study["config"]
    econ_dict = init_aiyagari_economy()
    econ_dict.update(LaborAR=0.3, LaborSD=0.2, CRRA=1.0, verbose=False)
    warm = study.get("policy_afunc")
    if warm and not os.environ.get("AIYAGARI_COLD_START"):
        # warm-start from the rule the study's policy was SOLVED under
        # (its final iteration's pre-update rule, not the post-update
        # afunc — one outer-update of difference is up to the 0.01 outer
        # tolerance, which would eat the rel=0.01 mean budget below).
        # Initial guess only; the solve re-certifies convergence.
        econ_dict.update(intercept_prev=list(warm["intercept"]),
                         slope_prev=list(warm["slope"]))
    agent_dict = init_aiyagari_agents()
    agent_dict.update(AgentCount=cfg["agent_count"])

    economy = AiyagariEconomy(seed=0, **econ_dict)
    agent = AiyagariType(**agent_dict)
    agent.cycles = 0
    agent.get_economy_data(economy)
    economy.agents = [agent]
    economy.make_Mrkv_history()
    sol = economy.solve(sim_method="panel")
    assert sol.converged

    keys = jax.random.split(jax.random.PRNGKey(12345), cfg["n_seeds"])
    k_init, k_sim = jax.random.split(keys[0])
    init = initial_panel(sol.calibration, cfg["agent_count"],
                         cfg.get("mrkv_init", 0), k_init)
    _, final = simulate_panel(sol.policy, sol.calibration,
                              jnp.asarray(sol.mrkv_hist), init, k_sim)
    assets = np.asarray(final.assets)

    ws = stats.wealth_stats(assets)
    ref = study["per_seed"][0]
    # same RNG keys + deterministic simulator: differences come only from
    # the re-solved policy (EGM tol 1e-6, KS tolerance 0.01)
    assert ws.mean == pytest.approx(ref["mean"], rel=0.01)
    assert ws.std == pytest.approx(ref["std"], rel=0.05)
    assert ws.median == pytest.approx(ref["median"], rel=0.05)
    assert ws.max == pytest.approx(ref["max"], rel=0.15)
    d = stats.lorenz_distance_vs_scf(assets)
    assert d == pytest.approx(ref["lorenz_vs_scf"], abs=0.02)
