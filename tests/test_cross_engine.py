"""Cross-engine validation: the framework's two DYNAMIC engines must
agree about aggregate fluctuations.

Engine A — the true Krusell-Smith machinery (reference-parity 4N-state
EGM, Monte-Carlo panel, estimated log-linear aggregate law) simulating a
pure 2-state TFP shock with employment held constant.

Engine B — the sequence-space linearization (compact N-state model, one
jax.jacrev through the transition path map, analytic MA moments) driven
by the AR(1) with the SAME persistence (1 - 2/spell) and stationary
standard deviation (half the TFP gap) as the 2-state chain.

The engines share no dynamic code: different state spaces, solvers,
simulators, and aggregation (regression-based law vs implicit-function
linearization).  Agreement of their volatility/persistence predictions
is a joint test of both — measured ~7% on std(log K) and ~0.002 on
autocorrelation, against MC sampling noise, the approximate KS law, the
2-state-vs-AR(1) substitution, and second-order effects."""

import jax
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_bisection_equilibrium
from aiyagari_hark_tpu.models.household import build_simple_model
from aiyagari_hark_tpu.models.jacobian import (
    business_cycle_moments,
    sequence_jacobians,
)
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy

from fixture_configs import (
    CROSS_ENGINE_SPELL as SPELL,
    CROSS_ENGINE_TFP_GAP as TFP_GAP,
    SOLVE_KWARGS,
    cross_engine_configs,
)

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


@pytest.fixture(scope="module")
def ks_moments():
    # 2000 agents x 7000 periods: the smallest budget that keeps the MC
    # moments inside the 20%/0.01 agreement tolerances with ~3x margin
    # (measured gap ~7% and ~0.002 at 3000x9000; shrunk in round 3 to cut
    # the single-core fixture cost ~40%, gaps remeasured ~8%/0.003).
    # Config + committed warm start: tests/fixture_configs.py.
    agent, econ = cross_engine_configs()
    sol = solve_ks_economy(agent, econ, **SOLVE_KWARGS["cross_engine"])
    assert sol.converged
    log_k = np.log(np.asarray(sol.history.A_prev)[econ.t_discard:])
    # hand engine B the preferences the KS solver ACTUALLY used (the
    # economy config's — build_ks_calibration reads them there), so a
    # recalibration moves both engines together
    return (log_k.std(), np.corrcoef(log_k[1:], log_k[:-1])[0, 1],
            econ.disc_fac, econ.crra)


def test_ks_simulation_matches_linearization(ks_moments):
    std_ks, ac1_ks, disc_fac, crra = ks_moments
    model = build_simple_model(labor_states=3, a_count=24,
                               dist_count=200)
    eq = solve_bisection_equilibrium(model, disc_fac, crra, 0.36, 0.08)
    jac = sequence_jacobians(model, disc_fac, crra, 0.36, 0.08, eq, 60)
    rho = 1.0 - 2.0 / SPELL
    sigma_z = TFP_GAP / 2.0
    mom = business_cycle_moments(jac, rho,
                                 sigma_z * np.sqrt(1.0 - rho ** 2))
    std_lin = float(mom.std["k"]) / float(eq.capital)
    ac1_lin = float(mom.autocorr1["k"])
    assert abs(std_lin / std_ks - 1.0) < 0.20
    assert abs(ac1_lin - ac1_ks) < 0.01
