"""Tests for the Krusell-Smith-machinery parity path: precompute, 4N-state
EGM, panel simulation, and the outer fixed point on a short horizon."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.ks_model import (
    AFuncParams,
    build_ks_calibration,
    precompute,
    solve_ks_household,
)
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from aiyagari_hark_tpu.models.simulate import (
    initial_panel,
    simulate_markov_history,
    simulate_panel,
)
from aiyagari_hark_tpu.ops.interp import interp_on_interp
from aiyagari_hark_tpu.utils.config import notebook_run_configs


@pytest.fixture(scope="module")
def cal():
    agent, econ = notebook_run_configs()
    return build_ks_calibration(agent, econ)


@pytest.fixture(scope="module")
def afunc(cal):
    return AFuncParams(intercept=jnp.zeros(2, dtype=cal.a_grid.dtype),
                       slope=jnp.ones(2, dtype=cal.a_grid.dtype))


def test_calibration_shapes(cal):
    assert cal.ind_transition.shape == (28, 28)
    assert cal.m_grid.shape == (15,)
    np.testing.assert_allclose(np.asarray(cal.ind_transition).sum(1),
                               np.ones(28), atol=1e-10)
    # state indexing: s = 4*labor + 2*agg + emp
    assert int(cal.labor_of_state[27]) == 6
    assert int(cal.agg_of_state[27]) == 1
    assert int(cal.emp_of_state[27]) == 1


def test_precompute_degenerate_aggregates(cal, afunc):
    """With ProdB=ProdG and UrateB=UrateG=0 (Aiyagari config), next-period
    prices depend only on M, not the aggregate state: columns for the same
    labor state must be identical across the 4 KS substates."""
    pre = precompute(afunc, cal)
    R = np.asarray(pre.R_next)   # [Mc, 28]
    for i in (0, 3, 6):
        block = R[:, 4 * i:4 * i + 4]
        np.testing.assert_allclose(block, block[:, :1].repeat(4, axis=1),
                                   rtol=1e-12)
    # m_next at the same (a, M) differs across labor states
    m = np.asarray(pre.m_next)
    assert not np.allclose(m[:, :, 1], m[:, :, 25])


def test_ks_egm_converges_and_is_sane(cal, afunc):
    policy, iters, diff, status = jax.jit(
        lambda a: solve_ks_household(a, cal))(afunc)
    assert float(diff) < 1e-6
    assert int(status) == 0   # solver_health.CONVERGED
    # consumption increasing in m at every (state, M-column)
    c = np.asarray(policy.c_knots)
    m = np.asarray(policy.m_knots)
    assert (np.diff(c, axis=-1) > 0).all()
    assert (np.diff(m, axis=-1) > 0).all()
    # degenerate KS states: policies identical across the 4 substates of a
    # labor state (aggregate shock off)
    np.testing.assert_allclose(c[4 * 3 + 0], c[4 * 3 + 3], rtol=1e-6)


def test_ks_policy_matches_simple_model_economics(cal, afunc):
    """At M = MSS the 4N-state policy evaluated at the steady-state prices
    should be close to the compact-model policy at the same prices (same
    economics, different machinery)."""
    policy, _, _, _ = solve_ks_household(afunc, cal)
    # With AFunc = identity (slope 1, intercept 0), perceived K' = M which is
    # NOT steady state; so compare both at the converged-AFunc sense loosely:
    # only check ordering: richer labor state consumes more at same m.
    mss = cal.steady_state.M
    m_test = jnp.linspace(2.0, 20.0, 7)
    c_low = interp_on_interp(m_test, mss, cal.m_grid,
                             policy.m_knots[1], policy.c_knots[1])
    c_high = interp_on_interp(m_test, mss, cal.m_grid,
                              policy.m_knots[25], policy.c_knots[25])
    assert (np.asarray(c_high) > np.asarray(c_low)).all()


def test_markov_history_properties(cal):
    hist = simulate_markov_history(cal.agg_transition, 0, 4000,
                                   jax.random.PRNGKey(0))
    h = np.asarray(hist)
    assert h[0] == 0
    assert set(np.unique(h)) <= {0, 1}
    # with symmetric 1/8 switching, both states occupied roughly half
    assert 0.3 < h.mean() < 0.7
    # mean spell duration near 8
    switches = (np.diff(h) != 0).sum()
    assert 4 < len(h) / max(switches, 1) < 16


@pytest.mark.slow
def test_panel_simulation_runs_and_is_stationary(cal, afunc):
    policy, _, _, _ = solve_ks_household(afunc, cal)
    hist = simulate_markov_history(cal.agg_transition, 0, 500,
                                   jax.random.PRNGKey(1))
    init = initial_panel(cal, 350, 0, jax.random.PRNGKey(2))
    out, final = jax.jit(lambda p, k: simulate_panel(p, cal, hist, init, k))(
        policy, jax.random.PRNGKey(3))
    A = np.asarray(out.A_prev)
    assert A.shape == (500,)
    assert np.isfinite(A).all() and (A > 0).all()
    # degenerate employment: urate identically zero
    np.testing.assert_allclose(np.asarray(out.urate), 0.0, atol=1e-12)
    # assets stay in a sane band (reference mean wealth 5.44)
    assert 1.0 < A[-100:].mean() < 12.0


@pytest.mark.slow
def test_seed_reproducibility(cal, afunc):
    """Fixes reference quirk §3.6-3: identical seeds -> identical histories."""
    policy, _, _, _ = solve_ks_household(afunc, cal)
    hist = simulate_markov_history(cal.agg_transition, 0, 200,
                                   jax.random.PRNGKey(1))
    init = initial_panel(cal, 70, 0, jax.random.PRNGKey(2))
    f = jax.jit(lambda k: simulate_panel(policy, cal, hist, init, k)[0].A_prev)
    a1, a2 = f(jax.random.PRNGKey(9)), f(jax.random.PRNGKey(9))
    np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
    a3 = f(jax.random.PRNGKey(10))
    assert not np.array_equal(np.asarray(a1), np.asarray(a3))


@pytest.mark.slow
def test_outer_loop_converges_short_horizon():
    agent, econ = notebook_run_configs()
    agent = agent.replace(agent_count=140)
    econ = econ.replace(act_T=1500, t_discard=300, verbose=False, max_loops=12)
    sol = solve_ks_economy(agent, econ, seed=0)
    assert sol.converged
    # equilibrium return in the reference's neighborhood (4.178 +- MC noise)
    assert 3.0 < sol.equilibrium_r_pct < 5.5
    assert len(sol.records) <= 12
    assert sol.records[-1].distance < econ.tolerance
