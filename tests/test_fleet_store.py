"""Two-PROCESS shared-store soak (ISSUE 15 satellite): racing puts and
gets on overlapping keys over one disk tier must produce zero
torn/corrupt entries, exactly-once solves per fingerprint (the
claim/lease election across real process boundaries — O_EXCL is only
meaningful against another process), and loser-serves-winner
bit-identity.

The children are real interpreters (``sys.executable -c``): each runs a
seeded op loop over an OVERLAPPING key set — claim; on a win "solve"
(a deterministic pure function of the key) and publish; on a loss poll
``get`` until the winner's entry appears and verify the bytes equal the
pure function's output bit-for-bit.  The parent asserts the fleet-wide
ledger afterwards from the children's result files and the directory
state."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from aiyagari_hark_tpu.scenarios.aiyagari import AIYAGARI_SCHEMA
from aiyagari_hark_tpu.serve.store import SolutionStore

_CHILD = r"""
import json, os, sys, time
import numpy as np

from aiyagari_hark_tpu.scenarios.aiyagari import AIYAGARI_SCHEMA as S
from aiyagari_hark_tpu.serve.store import SolutionStore, make_solution

store_dir, worker, seed, n_ops, n_keys, out = sys.argv[1:7]
worker, seed, n_ops, n_keys = int(worker), int(seed), int(n_ops), int(n_keys)


def row_for(key):
    # the deterministic "solve": a pure function of the key, so ANY
    # process solving key k must produce (and serve) these exact bytes
    rng = np.random.default_rng(key)
    row = rng.standard_normal(len(S.fields))
    row[S.idx(S.status)] = 0.0
    row[S.idx(S.root)] = 0.01 + key * 1e-4
    return row


store = SolutionStore(disk_path=store_dir, shared=True, lease_ttl_s=10.0,
                      owner=f"w{worker}", capacity=4)
rng = np.random.default_rng(seed)
solved, served, mismatches = [], 0, 0
for _ in range(n_ops):
    key = int(rng.integers(1, n_keys + 1))
    want = row_for(key)
    got = store.get(key)
    if got is None:
        verdict = store.claim(key)
        if verdict == "won":
            # hold the lease a moment: widen the window in which the
            # other process must lose the election, not re-solve
            time.sleep(0.002)
            store.publish(make_solution(
                (1.0 + key, 0.5, 0.2), want, group=777, key=key))
            solved.append(key)
            continue
        for _ in range(5000):
            got = store.get(key)
            if got is not None:
                break
            time.sleep(0.002)
    if got is None:
        mismatches += 1      # a loser must always see the publish
        continue
    served += 1
    if not np.array_equal(np.asarray(got.packed), want):
        mismatches += 1

with open(out, "w") as f:   # atomic-ok: test child's private result file
    json.dump({"solved": solved, "served": served,
               "mismatches": mismatches,
               "corrupt": store.integrity_counts()[
                   "store_corrupt_evictions"],
               "held": store.held_leases()}, f)
"""


@pytest.mark.parametrize("n_keys,n_ops", [(6, 40)])
def test_two_process_store_soak(tmp_path, n_keys, n_ops):
    store_dir = str(tmp_path / "shared")
    outs = [str(tmp_path / f"out{i}.json") for i in range(2)]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _CHILD, store_dir, str(i), str(100 + i),
         str(n_ops), str(n_keys), outs[i]],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True) for i in range(2)]
    results = []
    for i, p in enumerate(procs):
        _, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"child {i} failed:\n{err}"
        with open(outs[i]) as f:
            results.append(json.load(f))

    # zero torn/corrupt entries anywhere (checksum chain verified on
    # every cross-process load), zero bit mismatches (loser-serves-
    # winner), and no leases left behind
    for r in results:
        assert r["mismatches"] == 0
        assert r["corrupt"] == 0
        assert r["held"] == []
    assert SolutionStore(disk_path=store_dir, shared=True,
                         owner="audit").lease_files() == []

    # exactly-once fleet-wide: the union of both children's solve lists
    # has no duplicates — every fingerprint was solved by exactly one
    # process exactly once
    all_solved = results[0]["solved"] + results[1]["solved"]
    assert len(all_solved) == len(set(all_solved)), (
        f"duplicate solves across the fleet: {sorted(all_solved)}")

    # and the shared tier ends bit-identical to the pure function for
    # every solved key (a fresh process's audit read)
    audit = SolutionStore(disk_path=store_dir, shared=True,
                          owner="audit2", capacity=64)
    for key in set(all_solved):
        got = audit.get(key)
        assert got is not None
        rng = np.random.default_rng(key)
        want = rng.standard_normal(len(AIYAGARI_SCHEMA.fields))
        want[AIYAGARI_SCHEMA.idx(AIYAGARI_SCHEMA.status)] = 0.0
        want[AIYAGARI_SCHEMA.idx(AIYAGARI_SCHEMA.root)] = (
            0.01 + key * 1e-4)
        assert np.array_equal(np.asarray(got.packed), want)
