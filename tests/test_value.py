"""Tests for value-function recovery and welfare analytics (models/value.py)
— the working replacement for the reference's dead value machinery
(``MargValueFunc2D``, ``Aiyagari_Support.py:71-102``, SURVEY.md §2.2 D1).

Oracles: an exact closed-form value function (log utility, no labor income),
the envelope condition against finite differences, and homogeneity-based
welfare identities.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.household import (
    build_simple_model,
    solve_household,
    stationary_wealth,
)
from aiyagari_hark_tpu.models.value import (
    aggregate_welfare,
    consumption_equivalent,
    marginal_value_at,
    policy_value,
    value_at,
)


@pytest.fixture(scope="module")
def stochastic_case():
    model = build_simple_model(labor_states=5, a_count=48)
    R, W, beta, crra = 1.02, 1.1, 0.96, 2.0
    policy, _, _, _ = solve_household(R, W, model, beta, crra)
    vf, it, diff = jax.jit(
        lambda: policy_value(policy, R, W, model, beta, crra))()
    assert float(diff) < 1e-9
    return model, policy, vf, R, W, beta, crra


@pytest.mark.slow
def test_log_utility_closed_form():
    """With log utility and no labor income (W=0), the problem is
    cake-eating with return R: c = (1-beta) m exactly, and
    v(m) = ln((1-beta)m)/(1-beta) + beta ln(R beta)/(1-beta)^2 + ln(1-beta)
    terms — an exact oracle for both the EGM solver and the recovered value.
    """
    beta, R = 0.9, 1.05
    model = build_simple_model(labor_states=1, a_count=64, a_max=100.0)
    policy, _, _, _ = solve_household(R, 0.0, model, beta, 1.0)
    m_test = jnp.asarray([[2.0, 10.0, 30.0]])
    c = np.asarray(policy.c_knots)[0]
    m = np.asarray(policy.m_knots)[0]
    np.testing.assert_allclose(c[5:], (1 - beta) * m[5:], rtol=1e-5)

    vf, _, diff = policy_value(policy, R, 0.0, model, beta, 1.0)
    assert float(diff) < 1e-9
    v = np.asarray(value_at(vf, m_test, 1.0))[0]
    B = 1.0 / (1.0 - beta)
    A = (np.log(1 - beta) + beta * B * np.log(R * beta)) / (1 - beta)
    v_exact = A + B * np.log(np.asarray(m_test)[0])
    np.testing.assert_allclose(v, v_exact, rtol=2e-4)


def test_envelope_condition(stochastic_case):
    """dv/dm = u'(c(m)) at interior points — the envelope theorem ties the
    recovered level function to the policy it was built from."""
    model, policy, vf, R, W, beta, crra = stochastic_case
    m0 = jnp.linspace(3.0, 20.0, 6)
    h = 1e-4
    for s in (0, 2, 4):
        v_hi = np.asarray(value_at(vf, m0 + h, crra, state_idx=s))
        v_lo = np.asarray(value_at(vf, m0 - h, crra, state_idx=s))
        dv = (v_hi - v_lo) / (2 * h)
        vp = np.asarray(marginal_value_at(policy, m0, crra, state_idx=s))
        # the finite difference reads the piecewise-linear segment slope, so
        # agreement is limited by knot spacing (~0.5 near m=3), not by h
        np.testing.assert_allclose(dv, vp, rtol=3e-2)


def test_value_matches_monte_carlo_discounted_utility(stochastic_case):
    """The strongest oracle: v(m0, s0) = E sum beta^t u(c_t) estimated by
    forward-simulating the policy itself.  This is what exposed the
    constrained-segment interpolation bias the ``constrained_knots``
    augmentation now corrects (see ``policy_value`` docstring)."""
    from aiyagari_hark_tpu.ops.interp import interp1d
    from aiyagari_hark_tpu.ops.utility import crra_utility

    model, policy, vf, R, W, beta, crra = stochastic_case
    m0, s0 = 5.0, 2
    v_rec = float(value_at(vf, jnp.asarray(m0), crra, state_idx=s0))

    n_paths, horizon = 8000, 300
    logp = jnp.log(model.transition)

    def step(carry, key):
        m, s, disc, acc = carry
        c = jax.vmap(lambda mi, si: interp1d(mi, policy.m_knots[si],
                                             policy.c_knots[si]))(m, s)
        acc = acc + disc * crra_utility(c, crra)
        s_new = jax.random.categorical(key, logp[s]).astype(s.dtype)
        m_new = R * (m - c) + W * model.labor_levels[s_new]
        return (m_new, s_new, disc * beta, acc), None

    init = (jnp.full((n_paths,), m0),
            jnp.full((n_paths,), s0, dtype=jnp.int32),
            jnp.asarray(1.0), jnp.zeros((n_paths,)))
    keys = jax.random.split(jax.random.PRNGKey(7), horizon)
    (_, _, _, acc), _ = jax.lax.scan(step, init, keys)
    mc = np.asarray(acc)
    se = mc.std() / np.sqrt(n_paths)
    # within 4 std errors + a small discretization allowance
    assert abs(v_rec - mc.mean()) < 4 * se + 0.08, (v_rec, mc.mean(), se)


def test_value_increasing_and_monotone_in_state(stochastic_case):
    model, policy, vf, R, W, beta, crra = stochastic_case
    m0 = jnp.linspace(1.0, 25.0, 10)
    v_low = np.asarray(value_at(vf, m0, crra, state_idx=0))
    v_high = np.asarray(value_at(vf, m0, crra, state_idx=4))
    assert (np.diff(v_low) > 0).all() and (np.diff(v_high) > 0).all()
    # better labor state => strictly better off at the same resources
    assert (v_high > v_low).all()


@pytest.mark.slow
def test_aggregate_welfare_and_consumption_equivalent(stochastic_case):
    model, policy, vf, R, W, beta, crra = stochastic_case
    dist, _, _, _ = stationary_wealth(policy, R, W, model)
    wel = float(aggregate_welfare(vf, dist, R, W, model, crra))
    assert np.isfinite(wel)
    # a 5% wage rise is a strict welfare improvement
    policy2, _, _, _ = solve_household(R, 1.05 * W, model, beta, crra)
    vf2, _, _ = policy_value(policy2, R, 1.05 * W, model, beta, crra)
    wel2 = float(aggregate_welfare(vf2, dist, R, 1.05 * W, model, crra))
    assert wel2 > wel
    ce = float(consumption_equivalent(wel, wel2, crra, beta))
    assert 0.0 < ce < 0.10
    # identity: comparing an allocation with itself costs nothing
    np.testing.assert_allclose(
        float(consumption_equivalent(wel, wel, crra, beta)), 0.0, atol=1e-12)
    # homogeneity oracle: scaling consumption by (1+g) scales v by
    # (1+g)^(1-crra), so the recovered CE must be exactly g
    g = 0.03
    v_scaled = wel * (1 + g) ** (1 - crra)
    np.testing.assert_allclose(
        float(consumption_equivalent(wel, v_scaled, crra, beta)), g,
        rtol=1e-10)


def test_consumption_equivalent_log_branch():
    beta = 0.95
    # log utility: v shifts by ln(1+g)/(1-beta) under scaling
    v = -12.0
    g = 0.02
    v_alt = v + np.log(1 + g) / (1 - beta)
    np.testing.assert_allclose(
        float(consumption_equivalent(v, v_alt, 1.0, beta)), g, rtol=1e-10)
    # traced-crra path agrees with the static branch
    f = jax.jit(lambda c: consumption_equivalent(v, v_alt, c, beta))
    np.testing.assert_allclose(float(f(1.0)), g, rtol=1e-8)
    np.testing.assert_allclose(
        float(f(3.0)),
        float(consumption_equivalent(v, v_alt, 3.0, beta)), rtol=1e-8)


@pytest.mark.slow
def test_welfare_sweepable_under_jit_and_vmap(stochastic_case):
    """The whole recovery + welfare path compiles with traced scalars —
    welfare rides the Table II sweep like everything else."""
    model, policy, vf, R, W, beta, crra = stochastic_case

    def welfare(w_scale):
        p, _, _, _ = solve_household(R, w_scale * W, model, beta, crra)
        v, _, _ = policy_value(p, R, w_scale * W, model, beta, crra)
        dist, _, _, _ = stationary_wealth(p, R, w_scale * W, model)
        return aggregate_welfare(v, dist, R, w_scale * W, model, crra)

    out = jax.jit(jax.vmap(welfare))(jnp.asarray([1.0, 1.05]))
    assert out.shape == (2,)
    assert float(out[1]) > float(out[0])


def test_policy_value_direct_matches_iterative(stochastic_case):
    """The bounded-cost evaluation (raw-v LU + unrolled vnvrs Newton — the
    vmapped tax sweep's welfare path, VERDICT r3 weak-item 2) agrees with
    the while_loop fixed point to solver tolerance: same knots, same
    welfare, certified residual."""
    from aiyagari_hark_tpu.models.value import policy_value_direct

    model, policy, vf, R, W, beta, crra = stochastic_case
    vf_d, _, diff = jax.jit(
        lambda: policy_value_direct(policy, R, W, model, beta, crra))()
    assert float(diff) < 1e-8
    np.testing.assert_allclose(np.asarray(vf_d.vnvrs_knots),
                               np.asarray(vf.vnvrs_knots),
                               rtol=1e-6, atol=1e-7)
    dist, _, _, _ = stationary_wealth(policy, R, W, model)
    w_it = float(aggregate_welfare(vf, dist, R, W, model, crra))
    w_d = float(aggregate_welfare(vf_d, dist, R, W, model, crra))
    np.testing.assert_allclose(w_d, w_it, rtol=1e-7)


def test_policy_value_direct_log_utility_exact():
    """Direct evaluation against the closed-form cake-eating oracle (the
    same oracle as ``test_log_utility_closed_form``), through the log-CRRA
    branch of the Newton pieces ((u^{-1})' = F^crra with crra = 1)."""
    from aiyagari_hark_tpu.models.value import (policy_value_direct,
                                                value_at)

    beta, R = 0.9, 1.05
    model = build_simple_model(labor_states=1, a_count=64, a_max=100.0)
    policy, _, _, _ = solve_household(R, 0.0, model, beta, 1.0)
    vf, _, diff = policy_value_direct(policy, R, 0.0, model, beta, 1.0)
    # diff is the LOG-space residual: |Δv| ≤ diff/(1-beta) for log utility
    assert float(diff) < 1e-9
    m_test = jnp.asarray([[2.0, 10.0, 30.0]])
    v = np.asarray(value_at(vf, m_test, 1.0))[0]
    B = 1.0 / (1.0 - beta)
    A = (np.log(1 - beta) + beta * B * np.log(R * beta)) / (1 - beta)
    v_exact = A + B * np.log(np.asarray(m_test)[0])
    np.testing.assert_allclose(v, v_exact, rtol=2e-4)
