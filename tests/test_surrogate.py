"""Surrogate serving tier contract (ISSUE 17, DESIGN §15).

The invariants the tier must never break, with donors INJECTED into the
store (``make_solution(cert_level=0)``) so the interpolation path is
exercised without any real solve:

* a surrogate answer is ALWAYS tagged ``quality="surrogate"`` with its
  model-implied error bound and donor fingerprints, and is NEVER cached
  — the store holds only genuinely solved rows;
* too few / too distant donors, a bound over budget, and the seeded
  audit draw all ESCALATE (journaled ``SURROGATE_ESCALATED`` with the
  reason) to a genuine solve; an empty donor group is a plain cold
  miss, not an escalation;
* an audited escalation resolves through the real solve: the audit
  verdict (was the prediction inside its own bound?) and the
  ``LATTICE_REFINED`` refinement point are journaled;
* ``surrogate=None`` — and ``surrogate_ok=False`` per query — are
  bit-identical to the pre-surrogate engine.
"""

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import ObsConfig, read_journal
from aiyagari_hark_tpu.scenarios import get_scenario
from aiyagari_hark_tpu.serve import (
    EquilibriumService,
    SurrogatePolicy,
    fit_surrogate,
    make_query,
    make_solution,
)
from aiyagari_hark_tpu.solver_health import CONVERGED

KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
          max_bisect=16)
QUERY_CELL = (3.05, 0.55, 0.2)

# an exactly-linear r* surface over (σ, ρ): the local fit must recover
# it exactly, so the bound collapses to the solver-tolerance floor
def _plane(cell):
    return 0.02 + 0.004 * cell[0] + 0.01 * cell[1]


DONOR_CELLS = [(s, r, 0.2)
               for s in (2.8, 3.0, 3.2, 3.4)
               for r in (0.45, 0.65)]

POL = SurrogatePolicy(k=6, max_error_bound=0.02, max_distance=1.0,
                      min_donors=4)


def seed_donors(svc, group, cells=DONOR_CELLS, r_fn=_plane,
                cert_level=0, base_key=10_000):
    for i, c in enumerate(cells):
        packed = np.asarray([r_fn(c), 5.0, 0.9, 11.0, 500.0, 4000.0,
                             float(CONVERGED), 0.0, 4500.0, 0.0])
        svc.store.put(make_solution(c, packed, group, base_key + i,
                                    cert_level=cert_level))


def _svc(tmp_path=None, pol=POL):
    obs = None
    if tmp_path is not None:
        obs = ObsConfig(enabled=True,
                        journal_path=str(tmp_path / "events.jsonl"))
    return EquilibriumService(start_worker=False, max_batch=4,
                              ladder=(1, 2, 4), surrogate=pol, obs=obs)


# ---------------------------------------------------------------------------
# fit_surrogate unit properties.
# ---------------------------------------------------------------------------

def _fit(cells, r_fn, query=QUERY_CELL, floor=0.0, scale=None):
    scale = scale or get_scenario("aiyagari").cells.scale
    z = np.abs(np.asarray(cells) / np.asarray(scale)
               - np.asarray(query) / np.asarray(scale))
    return fit_surrogate(query, cells, [r_fn(c) for c in cells],
                         z.sum(axis=1), scale, floor=floor)


def test_fit_recovers_exact_plane_to_the_floor():
    fit = _fit(DONOR_CELLS, _plane, floor=1e-5)
    assert fit.linear
    assert fit.r_star == pytest.approx(_plane(QUERY_CELL), abs=1e-9)
    assert fit.bound == pytest.approx(1e-5)       # resid ~ulp < floor
    assert fit.kernel.sum() == pytest.approx(1.0)


def test_fit_drops_unspanned_columns():
    # DONOR_CELLS hold sd fixed: the sd offset column has zero ptp and
    # must be dropped, not degrade the whole fit to the weighted mean
    fit = _fit(DONOR_CELLS, _plane)
    assert fit.linear


def test_fit_curvature_inflates_bound():
    fit = _fit(DONOR_CELLS, lambda c: _plane(c) + 0.5 * (c[0] - 3.0) ** 2)
    assert fit.bound >= 2.0 * fit.resid > 0.0


def test_fit_mean_fallback_bills_spread():
    # 3 donors < dim_eff + 2: weighted-mean fallback, spread-based bound
    fit = _fit(DONOR_CELLS[:3], _plane)
    assert not fit.linear
    assert fit.kernel.sum() == pytest.approx(1.0)
    assert fit.bound > 0.0
    assert fit.spread == pytest.approx(
        max(_plane(c) for c in DONOR_CELLS[:3])
        - min(_plane(c) for c in DONOR_CELLS[:3]))


def test_fit_empty_donor_set_is_none():
    scale = get_scenario("aiyagari").cells.scale
    assert fit_surrogate(QUERY_CELL, [], [], [], scale) is None


# ---------------------------------------------------------------------------
# Serving: tagged, bounded, never cached.
# ---------------------------------------------------------------------------

def test_surrogate_served_tagged_and_never_cached(tmp_path):
    svc = _svc(tmp_path)
    q = make_query(*QUERY_CELL[:2], labor_sd=QUERY_CELL[2], **KW)
    seed_donors(svc, q.group())
    fut = svc.submit(q)
    assert fut.done()                     # answered at submit, no solve
    res = fut.result(0)
    assert res.quality == "surrogate"
    assert res.path == "surrogate"
    assert res.surrogate_error_bound is not None
    assert res.surrogate_error_bound <= POL.max_error_bound
    assert res.donor_keys and set(res.donor_keys) <= set(
        range(10_000, 10_000 + len(DONOR_CELLS)))
    # the donor surface is an exact plane: the fit serves it exactly
    assert res.r_star == pytest.approx(_plane(QUERY_CELL), abs=1e-9)
    # solver-effort counters are fiction and must read zero
    assert res.value("egm_iters") == 0.0
    # NEVER cached: the store still only holds the donors, and a
    # resubmit is served by the surrogate again — never as a cache hit
    assert svc.store.get(q.key()) is None
    assert svc.store.known() == len(DONOR_CELLS)
    res2 = svc.submit(q).result(0)
    assert res2.quality == "surrogate"
    snap = svc.metrics.snapshot()
    assert svc.metrics.served["surrogate"] == 2
    assert snap["surrogate_hit_rate"] == 1.0
    assert snap["surrogate_bound_p95"] <= POL.max_error_bound
    svc.close()
    ev = read_journal(str(tmp_path / "events.jsonl"),
                      event="SURROGATE_SERVED")
    assert len(ev) == 2 and ev[0]["donors"] == POL.k


def test_uncertified_donors_are_invisible_by_default():
    """require_certified=True (the default): a store full of
    UNCERTIFIED entries serves nothing — plain cold miss, no event —
    while require_certified=False accepts the same donors."""
    svc = _svc()
    q = make_query(*QUERY_CELL[:2], labor_sd=QUERY_CELL[2], **KW)
    seed_donors(svc, q.group(), cert_level=-1)
    fut = svc.submit(q)
    assert not fut.done()
    snap = svc.metrics.snapshot()
    assert snap["surrogate_escalations"] == 0
    svc.close(drain=False)

    svc2 = _svc(pol=POL.replace(require_certified=False))
    seed_donors(svc2, q.group(), cert_level=-1)
    assert svc2.submit(q).result(0).quality == "surrogate"
    svc2.close(drain=False)


# ---------------------------------------------------------------------------
# Escalations: table-driven, journaled with the reason.
# ---------------------------------------------------------------------------

def _far_donors(svc, group):
    seed_donors(svc, group,
                cells=[(s, r, 0.2) for s in (7.0, 7.5)
                       for r in (0.1, 0.3, 0.5)])


def _bad_donors(svc, group):
    # one wildly-off donor row: huge residual -> bound over budget
    seed_donors(svc, group)
    packed = np.asarray([0.5, 5.0, 0.9, 11.0, 500.0, 4000.0,
                         float(CONVERGED), 0.0, 4500.0, 0.0])
    svc.store.put(make_solution((3.1, 0.5, 0.2), packed, group, 10_099,
                                cert_level=0))


@pytest.mark.parametrize("pol,seeder,reason", [
    (POL.replace(min_donors=10), seed_donors, "too_few_donors"),
    (POL.replace(max_distance=0.3), _far_donors, "donor_too_far"),
    (POL, _bad_donors, "bound_exceeded"),
    (POL.replace(audit_fraction=1.0, audit_seed=7), seed_donors,
     "audit"),
])
def test_surrogate_escalates_with_reason(tmp_path, pol, seeder, reason):
    svc = _svc(tmp_path, pol=pol)
    q = make_query(*QUERY_CELL[:2], labor_sd=QUERY_CELL[2], **KW)
    seeder(svc, q.group())
    fut = svc.submit(q)
    assert not fut.done()                 # fell through to a real solve
    snap = svc.metrics.snapshot()
    assert snap["surrogate_escalations"] == 1
    assert snap["surrogate_escalation_rate"] == 1.0
    svc.close(drain=False)
    ev = read_journal(str(tmp_path / "events.jsonl"),
                      event="SURROGATE_ESCALATED")
    assert len(ev) == 1 and ev[0]["reason"] == reason


# ---------------------------------------------------------------------------
# The audited escalation resolves through a REAL solve (one solve).
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_audit_resolves_and_refines_lattice(tmp_path):
    pol = POL.replace(audit_fraction=1.0, audit_seed=7)
    svc = _svc(tmp_path, pol=pol)
    q = make_query(*QUERY_CELL[:2], labor_sd=QUERY_CELL[2], **KW)
    seed_donors(svc, q.group())
    fut = svc.submit(q)
    assert not fut.done()
    svc.flush()
    res = fut.result(120)
    # the real solve is served exact and PUBLISHED — the lattice
    # densified exactly where the surrogate was audited
    assert res.quality == "exact"
    assert svc.store.get(q.key()) is not None
    snap = svc.metrics.snapshot()
    assert snap["surrogate_audits"] == 1
    assert snap["surrogate_refinements"] == 1
    svc.close()
    jp = str(tmp_path / "events.jsonl")
    refined = read_journal(jp, event="LATTICE_REFINED")
    assert len(refined) == 1
    ev = refined[0]
    assert isinstance(ev["audit_ok"], bool)
    assert ev["surrogate_bound"] == pytest.approx(
        read_journal(jp, event="SURROGATE_ESCALATED")[0]["bound"])
    assert ev["audit_ok"] == (ev["surrogate_err"]
                              <= ev["surrogate_bound"])
    assert snap["surrogate_audit_failures"] == (0 if ev["audit_ok"]
                                                else 1)


# ---------------------------------------------------------------------------
# Off switches are bit-identical to the pre-surrogate engine.
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_surrogate_none_and_optout_bit_identical(tmp_path):
    cell = (3.0, 0.6)

    def solve(svc, **qkw):
        q = make_query(*cell, **KW, **qkw)
        fut = svc.submit(q)
        if not fut.done():
            svc.flush()
        return fut.result(120)

    # empty store: a policy-carrying service cold-misses identically
    plain = EquilibriumService(start_worker=False, max_batch=4,
                               ladder=(1, 2, 4))
    res_a = solve(plain)
    withpol = _svc(tmp_path)
    res_b = solve(withpol)
    # donor-filled store: surrogate_ok=False bypasses the tier and the
    # warm path answers exactly like a policy-free service's warm path
    donors = EquilibriumService(start_worker=False, max_batch=4,
                                ladder=(1, 2, 4))
    seed_donors(donors, make_query(*cell, **KW).group())
    res_c = solve(donors)
    withpol2 = _svc(pol=POL)
    seed_donors(withpol2, make_query(*cell, **KW).group())
    res_d = solve(withpol2, surrogate_ok=False)
    for got, want in ((res_b, res_a), (res_d, res_c)):
        assert got.quality == "exact"
        assert got.r_star == want.r_star          # bitwise
        assert got.values == want.values
        assert got.path == want.path
    # the opted-out query never touched the surrogate tier
    snap = withpol2.metrics.snapshot()
    assert withpol2.metrics.served["surrogate"] == 0
    assert snap["surrogate_escalations"] == 0
    for svc in (plain, withpol, donors, withpol2):
        svc.close()
    assert read_journal(str(tmp_path / "events.jsonl"),
                        event="SURROGATE_SERVED") == []
