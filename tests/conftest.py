"""Test environment: run everything on a virtual 8-device CPU mesh so
sharding/collective paths are exercised without TPU hardware, and enable
float64 so tests can compare against high-precision oracles.

Must set env vars before the first ``import jax`` anywhere in the test
process — conftest import order guarantees that under pytest.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
