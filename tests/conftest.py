"""Test environment: run everything on a virtual 8-device CPU mesh so
sharding/collective paths are exercised without TPU hardware, and enable
float64 so tests can compare against high-precision oracles.

GOTCHA (this image): ``jax`` is preloaded at interpreter startup by the axon
TPU platform plugin, and ``JAX_PLATFORMS=axon`` is exported in the shell — so
setting env vars here is too late to pick the platform.  ``jax.config.update``
still works because the backend itself initializes lazily, and ``XLA_FLAGS``
is also read at backend-init time (so the host-device-count flag does land).
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already preloaded; config still mutable)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache (VERDICT r3 weak-item 5): the suite's
# heavyweight modules jit large while_loop programs whose CPU compiles
# cost minutes per run; caching them across pytest invocations (same
# .jax_cache the bench/reproduce entry points use) makes every run after
# a code change warm.  The cache key covers HLO + jaxlib version, so
# solver changes recompile automatically.
from aiyagari_hark_tpu.utils.backend import enable_compilation_cache  # noqa: E402

enable_compilation_cache()

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_mesh():
    devs = jax.devices()
    assert jax.default_backend() == "cpu", f"tests must run on cpu, got {jax.default_backend()}"
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    yield


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
