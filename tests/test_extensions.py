"""Extension configs from BASELINE.json: true Krusell-Smith aggregate shocks
(the working replacement for the reference's broken D2/D3 intent, SURVEY.md
§2.2) and the fine-grid baseline (1000-pt assets x 15 income states —
N-generic shape change, fixing quirk §3.6-2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from aiyagari_hark_tpu.models.equilibrium import solve_calibration_lean
from aiyagari_hark_tpu.models.ks_solver import solve_ks_economy
from aiyagari_hark_tpu.utils.config import AgentConfig, EconomyConfig

pytestmark = pytest.mark.slow   # heavyweight equilibrium solves (fast profile: -m 'not slow')


# Classic Krusell-Smith (1998) calibration: bad state has lower TFP and
# 10% unemployment, good state 4%.
KS_ECON = EconomyConfig(labor_states=3, act_T=600, t_discard=100,
                        verbose=False, tolerance=0.03,
                        prod_b=0.99, prod_g=1.01,
                        urate_b=0.10, urate_g=0.04)
KS_AGENT = AgentConfig(labor_states=3, agent_count=200, a_count=16)


@pytest.fixture(scope="module")
def ks_solution():
    return solve_ks_economy(KS_AGENT, KS_ECON, seed=0, ks_employment=True)


def test_true_ks_converges(ks_solution):
    assert ks_solution.converged
    assert all(np.isfinite(r.distance) for r in ks_solution.records)


def test_true_ks_regression_fits(ks_solution):
    """With a real aggregate shock the per-state log-log saving rule should
    still fit tightly (KS's R^2 ~ .99+ in the original; small panel here)."""
    last = ks_solution.records[-1]
    assert min(last.r_squared) > 0.5
    assert 0.5 < min(last.slope) and max(last.slope) < 1.5


def test_true_ks_unemployment_tracks_aggregate_state(ks_solution):
    hist = ks_solution.history
    mrkv = np.asarray(hist.mrkv)
    urate = np.asarray(hist.urate)
    assert {0, 1} <= set(np.unique(mrkv))   # both states realized
    mean_bad = urate[mrkv == 0].mean()
    mean_good = urate[mrkv == 1].mean()
    assert mean_bad > mean_good
    assert abs(mean_bad - 0.10) < 0.03
    assert abs(mean_good - 0.04) < 0.03


def test_true_ks_unemployed_consume_less(ks_solution):
    """ks_employment=True: the unemployed earn zero, so at equal m their
    continuation differs — check policies differ across employment states."""
    pol = ks_solution.policy
    cal = ks_solution.calibration
    m = jnp.linspace(1.0, 10.0, 20)
    from aiyagari_hark_tpu.ops.interp import interp_on_interp
    M = cal.steady_state.M
    # state s = 4*labor + 2*agg + emp; labor=1, agg=0 (bad)
    c_unemp = interp_on_interp(m, M, cal.m_grid, pol.m_knots[4], pol.c_knots[4])
    c_emp = interp_on_interp(m, M, cal.m_grid, pol.m_knots[5], pol.c_knots[5])
    assert bool(jnp.all(c_unemp <= c_emp + 1e-6))
    assert float(jnp.max(jnp.abs(c_unemp - c_emp))) > 1e-4


def test_fine_grid_baseline():
    """1000-pt asset grid x 15 income states solves through the same code
    (shape-generic kernels) and reproduces the coarse-grid r* to ~10bp."""
    fine = jax.jit(lambda: solve_calibration_lean(
        3.0, 0.6, labor_states=15, a_count=1000, dist_count=1000))()
    coarse = jax.jit(lambda: solve_calibration_lean(3.0, 0.6))()
    r_fine = float(fine.r_star) * 100
    r_coarse = float(coarse.r_star) * 100
    assert np.isfinite(r_fine)
    assert 2.5 < r_fine < 4.17
    assert abs(r_fine - r_coarse) < 0.15


def test_true_ks_distribution_method():
    """True Krusell-Smith solved DETERMINISTICALLY: aggregate shocks on,
    the histogram simulator replacing the Monte-Carlo panel (Young's
    method — the modern KS standard).  The aggregate chain identifies the
    saving-rule regression, so no slope pinning; expected-mass employment
    flows make the state-conditional unemployment rates exact (the panel
    only matches them to rounding)."""
    sol = solve_ks_economy(KS_AGENT, KS_ECON, seed=0, ks_employment=True,
                           sim_method="distribution", dist_count=200)
    assert sol.converged
    hist = sol.history
    mrkv = np.asarray(hist.mrkv)
    urate = np.asarray(hist.urate)
    np.testing.assert_allclose(urate[mrkv == 0].mean(), 0.10, atol=1e-10)
    np.testing.assert_allclose(urate[mrkv == 1].mean(), 0.04, atol=1e-10)
    last = sol.records[-1]
    assert min(last.r_squared) > 0.9
    assert 0.8 < min(last.slope) and max(last.slope) < 1.3
    # deterministic: a second run reproduces the rule exactly
    sol2 = solve_ks_economy(KS_AGENT, KS_ECON, seed=0, ks_employment=True,
                            sim_method="distribution", dist_count=200)
    np.testing.assert_array_equal(np.asarray(sol.afunc.slope),
                                  np.asarray(sol2.afunc.slope))
