"""Unified observability layer (ISSUE 7, DESIGN §10): trace spans,
metrics registry, event journal — and THE event contract.

Two halves:

* unit tests of the three pillars — span nesting/export/subdivision,
  registry typing/round-trip/Prometheus text, journal append/read/torn
  tail — plus the no-op contract (ONE cached null context manager, zero
  allocations on the disabled path);
* the event CONTRACT, table-driven: every deterministic injection drill
  the previous PRs built (quarantine fault, SDC bit flip, transient
  fault, preemption, ledger corruption, deadline expiry, store
  eviction, serve-path certification failure, precision escalation)
  re-run with the journal enabled must yield EXACTLY the matching typed
  event(s), with the right ``run_id``/cell attributes — and obs
  disabled must change ZERO solver bits.

Solver configs deliberately mirror ``tests/test_resilience.py`` (sweep
drills) and ``tests/test_serve*.py`` (serve drills) so this module
rides their warm jit caches instead of compiling its own programs.
"""

import json
import threading
import warnings

import numpy as np
import pytest

from aiyagari_hark_tpu.obs import (
    EVENT_TYPES,
    EventJournal,
    MetricsRegistry,
    NULL_OBS,
    NULL_SPAN_CM,
    ObsConfig,
    Tracer,
    build_obs,
    default_registry,
    emit_event,
    new_run_id,
    read_journal,
    reset_default_registry,
    resolve_obs,
    trace_nesting_ok,
)
from aiyagari_hark_tpu.utils.config import SweepConfig
from aiyagari_hark_tpu.utils.resilience import RetryPolicy

# Sweep drill config: SAME cache keys as tests/test_resilience.py.
KW = dict(a_count=12, dist_count=48, labor_states=4, r_tol=1e-5,
          max_bisect=30)
SMALL = SweepConfig(crra_values=(1.0, 5.0), rho_values=(0.0, 0.9),
                    schedule="balanced", n_buckets=2)
# Lockstep shape for the resume/corruption drill — mirrors
# tests/test_verify.py's SMALL so after_bucket=0 leaves every row solved
# (the corrupted row must be one the ledger claims solved).
LOCKSTEP = SweepConfig(crra_values=(1.0, 3.0), rho_values=(0.3, 0.6))
# Serve drill config: SAME cache keys as tests/test_serve.py /
# tests/test_serve_integrity.py.
SERVE_KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-4,
                max_bisect=16)
CERT_KW = dict(a_count=10, dist_count=32, labor_states=3, r_tol=1e-5,
               max_bisect=24)


# ---------------------------------------------------------------------------
# Pillar 1: tracer.
# ---------------------------------------------------------------------------

def test_new_run_id_sortable_and_unique():
    a, b = new_run_id(), new_run_id()
    assert a != b
    assert a.startswith("run-")
    # filesystem- and grep-safe: no separators beyond '-'
    assert all(c.isalnum() or c == "-" for c in a)


def test_tracer_nested_spans_export_chrome_trace():
    tr = Tracer(run_id="run-test")
    with tr.span("outer", cells=4) as sp:
        sp.annotate(extra="x")
        with tr.span("inner"):
            pass
    trace = tr.chrome_trace()
    events = trace["traceEvents"]
    assert len(events) == 2
    assert {e["name"] for e in events} == {"outer", "inner"}
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in events)
    # the correlation contract: run_id on every event AND in metadata
    assert all(e["args"]["run_id"] == "run-test" for e in events)
    assert trace["metadata"]["run_id"] == "run-test"
    outer = next(e for e in events if e["name"] == "outer")
    assert outer["args"]["cells"] == 4 and outer["args"]["extra"] == "x"
    assert trace_nesting_ok(trace)


def test_span_subdivide_materializes_synthetic_children():
    """Phase spans from returned counters (the jit-boundary answer):
    subdivide partitions the parent wall proportionally, children are
    marked synthetic and stay inside the parent."""
    tr = Tracer()
    with tr.span("bucket") as sp:
        pass
    sp.subdivide({"descent": 3.0, "polish": 1.0, "zero": 0.0},
                 prefix="phase/")
    events = tr.chrome_trace()["traceEvents"]
    names = [e["name"] for e in events]
    assert "phase/descent" in names and "phase/polish" in names
    assert "phase/zero" not in names            # zero-weight parts dropped
    parent = next(e for e in events if e["name"] == "bucket")
    kids = [e for e in events if e["name"].startswith("phase/")]
    assert all(e["args"]["synthetic"] for e in kids)
    for e in kids:
        assert e["ts"] >= parent["ts"] - 1e-6
        assert (e["ts"] + e["dur"]
                <= parent["ts"] + parent["dur"] + 1e-6)
    d = next(e for e in kids if e["name"] == "phase/descent")
    p = next(e for e in kids if e["name"] == "phase/polish")
    assert d["dur"] == pytest.approx(3.0 * p["dur"], rel=0.05, abs=1e-3)
    assert trace_nesting_ok(tr.chrome_trace())


def test_tracer_is_thread_safe_with_per_thread_rows():
    tr = Tracer()
    # barrier keeps all four threads alive at once: thread idents are
    # recycled after join, and concurrent threads are the case the
    # per-thread tid rows exist for
    gate = threading.Barrier(4)

    def work():
        with tr.span("t"):
            with tr.span("u"):
                gate.wait(timeout=10)

    threads = [threading.Thread(target=work) for _ in range(4)]
    with tr.span("main"):
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    trace = tr.chrome_trace()
    assert len(trace["traceEvents"]) == 9
    assert len({e["tid"] for e in trace["traceEvents"]}) == 5
    assert trace_nesting_ok(trace)


def test_trace_nesting_ok_rejects_partial_overlap():
    bad = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": 10.0, "tid": 0},
        {"name": "b", "ph": "X", "ts": 5.0, "dur": 10.0, "tid": 0}]}
    assert not trace_nesting_ok(bad)
    neg = {"traceEvents": [
        {"name": "a", "ph": "X", "ts": 0.0, "dur": -1.0, "tid": 0}]}
    assert not trace_nesting_ok(neg)


def test_save_chrome_trace_is_atomic_and_loadable(tmp_path):
    tr = Tracer()
    with tr.span("a"):
        pass
    path = str(tmp_path / "trace.json")
    tr.save_chrome_trace(path)
    with open(path) as f:
        trace = json.load(f)
    assert len(trace["traceEvents"]) == 1
    assert not list(tmp_path.glob("*.tmp"))     # atomic writer cleaned up


# ---------------------------------------------------------------------------
# Pillar 2: metrics registry.
# ---------------------------------------------------------------------------

def test_registry_instruments_record_and_type_check():
    reg = MetricsRegistry()
    c = reg.counter("aiyagari_test_total", "help text")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1.0)                             # counters never decrease
    g = reg.gauge("aiyagari_test_depth")
    g.set(7.0)
    g.inc(-2.0)                                 # gauges may
    assert g.value == 5.0
    h = reg.histogram("aiyagari_test_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count == 3 and h.sum == pytest.approx(5.55)
    assert h.cumulative_counts() == [1, 2, 3]
    # get-or-create: same name+kind returns the same instrument
    assert reg.counter("aiyagari_test_total") is c
    # same name, different kind: typed error, no silent shadowing
    with pytest.raises(ValueError):
        reg.gauge("aiyagari_test_total")
    # non-Prometheus names are rejected at creation
    with pytest.raises(ValueError):
        reg.counter("bad name!")


def test_registry_snapshot_roundtrip_and_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("aiyagari_events_total", "events").inc(4)
    reg.gauge("aiyagari_wall_seconds").set(1.25)
    h = reg.histogram("aiyagari_lat_seconds", buckets=(0.001, 0.1))
    h.observe(0.0005)
    h.observe(0.05)
    snap = reg.snapshot()
    assert MetricsRegistry.restore(snap).snapshot() == snap
    text = reg.prometheus_text()
    assert "# TYPE aiyagari_events_total counter" in text
    assert "aiyagari_events_total 4" in text
    assert 'aiyagari_lat_seconds_bucket{le="+Inf"} 2' in text
    assert "aiyagari_lat_seconds_count 2" in text
    assert "# HELP aiyagari_events_total events" in text


def test_default_registry_is_process_global_and_resettable():
    reset_default_registry()
    try:
        a = default_registry()
        assert default_registry() is a
        a.counter("aiyagari_ambient_total").inc()
        reset_default_registry()
        assert default_registry() is not a
    finally:
        reset_default_registry()


def test_compile_counter_publishes_into_registry():
    from aiyagari_hark_tpu.utils.timing import CompileCounter

    c = CompileCounter()
    c.compile_events, c.compile_seconds = 3, 1.5
    c.cache_hits, c.cache_misses = 2, 1
    reg = MetricsRegistry()
    c.publish(reg)
    snap = reg.snapshot()
    assert snap["aiyagari_xla_compile_events"]["value"] == 3
    assert snap["aiyagari_xla_cache_misses"]["value"] == 1
    c.publish(None)                             # tolerated no-op


# ---------------------------------------------------------------------------
# Pillar 3: event journal.
# ---------------------------------------------------------------------------

def test_journal_emits_typed_lines_and_reader_filters(tmp_path):
    path = str(tmp_path / "events.jsonl")
    j = EventJournal(path, "run-a", clock=lambda: 12.0)
    j.emit("QUARANTINE", cell=3, crra=5.0)
    j.emit("BUCKET_LAUNCH", bucket=0)
    EventJournal(path, "run-b").emit("QUARANTINE", cell=1)
    assert j.emitted == 2
    recs = read_journal(path)
    assert len(recs) == 3                       # appends never truncate
    mine = read_journal(path, run_id="run-a")
    assert [r["event"] for r in mine] == ["QUARANTINE", "BUCKET_LAUNCH"]
    assert mine[0] == {"ts": 12.0, "run_id": "run-a",
                       "event": "QUARANTINE", "cell": 3, "crra": 5.0}
    q = read_journal(path, event="QUARANTINE")
    assert {r["run_id"] for r in q} == {"run-a", "run-b"}


def test_journal_rejects_unknown_event_type(tmp_path):
    j = EventJournal(str(tmp_path / "e.jsonl"), "run-x")
    with pytest.raises(ValueError, match="unknown journal event type"):
        j.emit("TOTALLY_NEW_THING")
    assert "QUARANTINE" in EVENT_TYPES          # vocabulary is exported


def test_journal_torn_tail_skipped_with_warning(tmp_path):
    path = str(tmp_path / "e.jsonl")
    j = EventJournal(path, "run-a")
    j.emit("RUN_START")
    with open(path, "ab") as f:  # atomic-ok: test simulates the torn tail
        f.write(b'{"ts": 1, "run_id": "run-a", "event": "RUN_')
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        recs = read_journal(path)
    assert [r["event"] for r in recs] == ["RUN_START"]
    assert any("unparseable" in str(x.message) for x in w)
    assert read_journal(str(tmp_path / "missing.jsonl")) == []


# ---------------------------------------------------------------------------
# The runtime bundle: resolve/activate/no-op contracts.
# ---------------------------------------------------------------------------

def test_disabled_path_is_one_cached_null_context_manager():
    """THE no-op contract (ISSUE 7 tentpole): disabled spans are ONE
    process-wide nullcontext — no allocation, no clock read."""
    assert NULL_OBS.span("sweep/bucket", bucket=1) is NULL_SPAN_CM
    assert NULL_OBS.span("anything") is NULL_SPAN_CM
    with NULL_OBS.span("x") as sp:
        sp.annotate(a=1)                        # all mutators no-op
        sp.subdivide({"descent": 3})
    NULL_OBS.event("QUARANTINE", cell=1)        # journals nothing
    NULL_OBS.counter("aiyagari_x_total").inc()  # records nothing
    assert NULL_OBS.counter("aiyagari_x_total").value == 0.0
    NULL_OBS.close()                            # idempotent no-op


def test_build_and_resolve_obs_contract(tmp_path):
    assert build_obs(None) is NULL_OBS
    assert build_obs(ObsConfig(enabled=False)) is NULL_OBS
    assert resolve_obs(None) == (NULL_OBS, False)
    cfg = ObsConfig(enabled=True,
                    journal_path=str(tmp_path / "j.jsonl"))
    obs, owned = resolve_obs(cfg)
    assert obs is not NULL_OBS and owned        # built here -> owned
    passed, owned2 = resolve_obs(obs)
    assert passed is obs and not owned2         # shared bundle -> not owned
    with pytest.raises(TypeError):
        resolve_obs("yes please")
    obs.close()


def test_run_lifecycle_events_and_idempotent_close(tmp_path):
    jp = str(tmp_path / "j.jsonl")
    tp = str(tmp_path / "t.json")
    obs = build_obs(ObsConfig(enabled=True, run_id="run-lc",
                              journal_path=jp, trace_path=tp))
    with obs.span("work"):
        pass
    obs.close()
    obs.close()                                 # second close: no-op
    events = [r["event"] for r in read_journal(jp, run_id="run-lc")]
    assert events == ["RUN_START", "RUN_END"]
    with open(tp) as f:
        trace = json.load(f)
    assert trace["metadata"]["run_id"] == "run-lc"
    assert len(trace["traceEvents"]) == 1


def test_emit_event_without_active_scope_is_a_noop(tmp_path):
    emit_event("QUARANTINE", cell=0)            # no scope: silently dropped
    jp = str(tmp_path / "j.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    with obs.activate():
        emit_event("QUARANTINE", cell=7)
    emit_event("QUARANTINE", cell=8)            # deactivated again
    cells = [r["cell"] for r in read_journal(jp, event="QUARANTINE")]
    assert cells == [7]
    obs.close()


# ---------------------------------------------------------------------------
# The event contract, table-driven: injected drill -> typed event(s).
# ---------------------------------------------------------------------------

def _sweep_journal(tmp_path, name, cfg=None, **kwargs):
    """Run a SMALL sweep with the journal on; return (result, records,
    run_id) with records filtered to this run."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep

    jp = str(tmp_path / f"{name}.jsonl")
    res = run_table2_sweep(
        SMALL if cfg is None else cfg,
        obs=ObsConfig(enabled=True, journal_path=jp),
        **{**KW, **kwargs})
    recs = read_journal(jp)
    run_ids = {r["run_id"] for r in recs}
    assert len(run_ids) == 1                    # one run, one id
    return res, recs, run_ids.pop()


# One row per injection drill: (name, config, sweep kwargs, expected
# event type, expected per-event attrs).  Each drill must yield EXACTLY
# one matching event (the injected == recorded acceptance).
SWEEP_DRILLS = [
    ("quarantine_fault", SMALL,
     dict(inject_fault={"cell": 1, "at_iter": 1, "mode": "nan"},
          max_retries=2),
     "QUARANTINE", {"cell": 1, "recovered": True}),
    ("sdc_bit_flip", SMALL.replace(recheck_fraction=1.0),
     dict(inject_sdc={"cell": 1, "bit": 30}, quarantine=False),
     "SDC_SUSPECTED", {"cell": 1}),
    ("transient_fault", SMALL,
     dict(inject_transient={"at_call": 0, "times": 1},
          retry=RetryPolicy(sleep=lambda s: None)),
     "RETRY_TRANSIENT", {"attempt": 1}),
]


@pytest.mark.parametrize("name,cfg,kwargs,etype,attrs", SWEEP_DRILLS,
                         ids=[d[0] for d in SWEEP_DRILLS])
def test_injected_drill_yields_exactly_one_typed_event(
        tmp_path, name, cfg, kwargs, etype, attrs):
    res, recs, run_id = _sweep_journal(tmp_path, name, cfg=cfg, **kwargs)
    matches = [r for r in recs if r["event"] == etype]
    assert len(matches) == 1, (etype, recs)
    for k, v in attrs.items():
        assert matches[0][k] == v, (k, matches[0])
    assert matches[0]["run_id"] == run_id
    # the run's framing events always bracket the drill
    events = [r["event"] for r in recs]
    assert events[0] == "RUN_START" and events[-1] == "RUN_END"
    assert events.count("BUCKET_LAUNCH") == 2   # n_buckets launches


def test_clean_sweep_journals_only_lifecycle_events(tmp_path):
    res, recs, _ = _sweep_journal(tmp_path, "clean")
    assert not np.isnan(res.r_star_pct).any()
    kinds = {r["event"] for r in recs}
    assert kinds == {"RUN_START", "BUCKET_LAUNCH", "RUN_END"}
    # bucket launches carry cell lists covering every cell exactly once
    cells = sorted(c for r in recs if r["event"] == "BUCKET_LAUNCH"
                   for c in r["cells"])
    assert cells == list(range(4))


def test_precision_escalation_journaled_per_cell(tmp_path):
    """A stalled descent phase under the mixed ladder escalates
    in-program (DESIGN §5) while the cell stays healthy — the journal
    names each escalated cell.  (Mode "nan" would poison the lean
    bisection's descent-only bracket trips too, routing through
    quarantine instead — a different drill.)"""
    res, recs, _ = _sweep_journal(tmp_path, "escalate",
                                  precision="mixed",
                                  descent_fault_iter=0,
                                  descent_fault_mode="stall")
    esc = [r for r in recs if r["event"] == "PRECISION_ESCALATED"]
    expected = {int(i) for i in
                np.nonzero(res.precision_escalations > 0)[0]}
    assert expected                              # the drill fired
    assert {r["cell"] for r in esc} == expected
    assert len(esc) == len(expected)             # exactly one per cell


def test_interrupt_resume_and_ledger_corruption_events(tmp_path):
    """The resilience seams end-to-end: injected preemption journals
    INTERRUPTED; the resumed run journals RESUME_RESTORE; a ledger row
    corrupted between the two journals INTEGRITY_FAILED — each exactly
    once, under that run's id."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep
    from aiyagari_hark_tpu.utils.resilience import (
        Interrupted,
        clear_interrupt,
    )
    from aiyagari_hark_tpu.verify import corrupt_ledger_row

    ledger = str(tmp_path / "ledger.npz")
    jp = str(tmp_path / "events.jsonl")
    try:
        with pytest.raises(Interrupted):
            run_table2_sweep(
                LOCKSTEP, resume_path=ledger,
                obs=ObsConfig(enabled=True, journal_path=jp),
                inject_preempt={"after_bucket": 0, "mode": "flag"},
                **KW)
    finally:
        clear_interrupt()
    first = read_journal(jp)
    ints = [r for r in first if r["event"] == "INTERRUPTED"]
    assert len(ints) == 1 and ints[0]["resume_path"] == ledger
    # even the interrupted run closes its journal (owned bundle)
    assert first[-1]["event"] == "RUN_END"

    corrupt_ledger_row(ledger, cell=1, bit=21)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        resumed = run_table2_sweep(
            LOCKSTEP, resume_path=ledger,
            obs=ObsConfig(enabled=True, journal_path=jp), **KW)
    run1 = ints[0]["run_id"]
    second = [r for r in read_journal(jp) if r["run_id"] != run1]
    integ = [r for r in second if r["event"] == "INTEGRITY_FAILED"]
    assert len(integ) == 1
    assert integ[0]["boundary"] == "ledger" and integ[0]["cells"] == [1]
    restores = [r for r in second if r["event"] == "RESUME_RESTORE"]
    assert len(restores) == 1
    assert restores[0]["cells_restored"] >= 1
    assert restores[0]["corrupt_cells"] == [1]
    # and the recomputed result is still clean
    clean = run_table2_sweep(LOCKSTEP, **KW)
    assert np.array_equal(clean.r_star_pct, resumed.r_star_pct)


def test_serve_deadline_and_metrics_mirror(tmp_path):
    """Serve seams: an expired deadline journals DEADLINE_EXCEEDED and
    counts in the registry; close() mirrors the ServeMetrics snapshot
    into the same registry (one scrapeable view, ISSUE 7 tentpole)."""
    from aiyagari_hark_tpu.serve import EquilibriumService, make_query

    jp = str(tmp_path / "serve.jsonl")
    obs = build_obs(ObsConfig(enabled=True, journal_path=jp))
    t = [0.0]
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4), clock=lambda: t[0],
                             obs=obs)
    expired = svc.submit(make_query(3.0, 0.6, **SERVE_KW), deadline=0.5)
    t[0] = 1.0
    svc.flush()
    assert expired.done() and expired.exception(0) is not None
    svc.close()
    dead = read_journal(jp, event="DEADLINE_EXCEEDED",
                        run_id=obs.run_id)
    assert len(dead) == 1
    assert dead[0]["waited_s"] == pytest.approx(1.0)
    snap = obs.registry.snapshot()
    assert snap["aiyagari_serve_deadline_expirations_total"][
        "value"] == 1
    # ServeMetrics mirrored on close without changing its own API
    assert snap["aiyagari_serve_deadline_expirations"]["value"] == 1
    obs.close()


def test_store_corrupt_eviction_journaled(tmp_path):
    """A corrupt disk entry discovered at restart journals exactly one
    STORE_EVICT_CORRUPT (and the service-owned journal sees it even
    though the store found it during init)."""
    from aiyagari_hark_tpu.serve import EquilibriumService
    from aiyagari_hark_tpu.verify import corrupt_store_entry

    d = str(tmp_path / "store")
    svc = EquilibriumService(start_worker=False, max_batch=4,
                             ladder=(1, 2, 4), disk_path=d)
    svc.query(3.0, 0.6, **SERVE_KW)
    svc.close()
    corrupt_store_entry(d, mode="perturb", amplitude=1e-3)
    jp = str(tmp_path / "store.jsonl")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        svc2 = EquilibriumService(
            start_worker=False, max_batch=4, ladder=(1, 2, 4),
            disk_path=d, obs=ObsConfig(enabled=True, journal_path=jp))
        svc2.close()
    evs = read_journal(jp, event="STORE_EVICT_CORRUPT")
    assert len(evs) == 1
    assert evs[0]["tier"] == "disk"
    assert evs[0]["reason"] == "checksum mismatch"


def test_serve_certification_failure_journaled(tmp_path):
    """certify_before_cache + injected lane corruption: the failed
    future journals CERT_FAILED with the serve attribution."""
    from aiyagari_hark_tpu.serve import (
        CertificationFailed,
        EquilibriumService,
        make_query,
    )

    jp = str(tmp_path / "cert.jsonl")
    svc = EquilibriumService(
        start_worker=False, max_batch=4, ladder=(1, 2, 4),
        certify_before_cache=True,
        inject_corrupt_lane={"at_launch": 0, "lane": 0,
                             "amplitude": 3e-3},
        obs=ObsConfig(enabled=True, journal_path=jp))
    fut = svc.submit(make_query(3.0, 0.6, **CERT_KW))
    svc.flush()
    with pytest.raises(CertificationFailed):
        fut.result(0)
    svc.close()
    evs = read_journal(jp, event="CERT_FAILED")
    assert len(evs) == 1 and evs[0]["where"] == "serve"
    assert evs[0]["cell"][:2] == [3.0, 0.6]


# ---------------------------------------------------------------------------
# No-op mode: disabled obs changes ZERO solver bits.
# ---------------------------------------------------------------------------

def _assert_sweep_identical(a, b):
    for f in ("r_star_pct", "saving_rate_pct", "capital", "excess"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f)),
                              equal_nan=True), f
    for f in ("bisect_iters", "egm_iters", "dist_iters", "status",
              "retries", "bucket", "descent_steps", "polish_steps",
              "precision_escalations"):
        assert np.array_equal(np.asarray(getattr(a, f)),
                              np.asarray(getattr(b, f))), f


def test_obs_enabled_vs_disabled_is_bit_identical(tmp_path):
    """The acceptance pin: tracing + journaling a sweep changes no
    solver bits — obs=None, ObsConfig(enabled=False) (on the config)
    and a fully enabled bundle all produce the same SweepResult."""
    from aiyagari_hark_tpu.parallel.sweep import run_table2_sweep

    base = run_table2_sweep(SMALL, **KW)
    off = run_table2_sweep(SMALL.replace(obs=ObsConfig(enabled=False)),
                           **KW)
    on = run_table2_sweep(
        SMALL, obs=ObsConfig(enabled=True,
                             journal_path=str(tmp_path / "j.jsonl"),
                             trace_path=str(tmp_path / "t.json")),
        **KW)
    _assert_sweep_identical(base, off)
    _assert_sweep_identical(base, on)
    # and the enabled run's trace actually materialized, nested sanely
    with open(tmp_path / "t.json") as f:
        trace = json.load(f)
    assert trace_nesting_ok(trace)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "sweep/run" in names and "sweep/bucket" in names
    # counter-derived synthetic children (reference precision: every
    # inner step is polish, so only the polish child materializes)
    assert "sweep/phase/polish" in names
