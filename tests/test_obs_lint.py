"""check_obs_events lint (ISSUE 7 satellite): every typed framework
error construction and every quarantine/retry/evict seam must leave a
journal trail (or carry an explicit ``# obs-ok`` waiver) — run in
tier-1 so a seam added without its event cannot regress in, with
fixture tests proving the lint actually fires on the patterns it
guards."""

import importlib.util
import os


def _load_lint():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "check_obs_events",
        os.path.join(repo, "scripts", "check_obs_events.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod, repo


def test_obs_event_lint_is_clean():
    """The package and entry points contain no unjournaled typed-error
    sites or silent seams — failing here, not in code review."""
    mod, repo = _load_lint()
    findings = mod.scan(repo)
    assert findings == [], "\n".join(
        f"{rel}:{line}: {msg}" for rel, line, msg in findings)


def test_obs_event_lint_covers_instrumented_seams():
    """Pin the walk's coverage of the modules that own lifecycle seams,
    instead of trusting it silently."""
    mod, repo = _load_lint()
    rels = {os.path.relpath(t, repo).replace(os.sep, "/")
            for t in mod.scan_targets(repo)}
    for required in ("aiyagari_hark_tpu/utils/resilience.py",
                     "aiyagari_hark_tpu/utils/fingerprint.py",
                     "aiyagari_hark_tpu/serve/service.py",
                     "aiyagari_hark_tpu/serve/store.py",
                     "aiyagari_hark_tpu/parallel/sweep.py",
                     "aiyagari_hark_tpu/models/ks_solver.py",
                     "aiyagari_hark_tpu/facade.py",
                     "aiyagari_hark_tpu/obs/runtime.py",
                     "aiyagari_hark_tpu/obs/profile.py",
                     "aiyagari_hark_tpu/obs/regress.py",
                     "bench.py"):
        assert required in rels, required


def test_lint_requires_emit_in_new_perf_seams():
    """The ISSUE 10 dump/flag sites are seam functions: stripping their
    journal event must be a lint failure, structurally."""
    mod, _ = _load_lint()
    assert "dump_flight" in mod.SEAM_DEFS
    assert "evaluate_history" in mod.SEAM_DEFS
    findings = mod.scan_source(
        "def dump_flight(self, reason):\n"
        "    return write(reason)\n", "fixture.py")
    assert len(findings) == 1 and "seam function" in findings[0][2]


def test_lint_fires_on_unjournaled_typed_raise():
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "def solve(x):\n"
        "    if x < 0:\n"
        "        raise SolverDivergenceError('diverged', status=3)\n"
        "    return x\n", "fake.py")
    assert [(rel, line) for rel, line, _ in findings] == [("fake.py", 3)]


def test_lint_fires_on_set_exception_construction():
    """Typed errors handed to Future.set_exception (never ``raise``d)
    are seams too — the serve path's DeadlineExceeded pattern."""
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "def expire(p):\n"
        "    p.future.set_exception(DeadlineExceeded(p.cell, 0, 1.0))\n",
        "fake.py")
    assert [line for _, line, _ in findings] == [2]


def test_lint_accepts_emitting_and_waived_sites():
    mod, _ = _load_lint()
    # emission evidence in the enclosing function
    assert mod.scan_source(
        "def expire(p, obs):\n"
        "    obs.event('DEADLINE_EXCEEDED', cell=p.cell)\n"
        "    p.future.set_exception(DeadlineExceeded(p.cell, 0, 1.0))\n",
        "fake.py") == []
    # module-level hook spelling
    assert mod.scan_source(
        "def verify(row):\n"
        "    emit_event('INTEGRITY_FAILED', boundary='x')\n"
        "    raise IntegrityError('bad bytes')\n", "fake.py") == []
    # explicit waiver
    assert mod.scan_source(
        "def rewrap(e):\n"
        "    raise IntegrityError(str(e))  # obs-ok: re-wrap, journaled"
        " upstream\n", "fake.py") == []


def test_lint_exempts_error_class_definitions():
    """``class DeadlineExceeded(...)`` bodies construct nothing — the
    definition (incl. subclasses of typed errors) is not a seam."""
    mod, _ = _load_lint()
    assert mod.scan_source(
        "class DeadlineExceeded(ServeError):\n"
        "    def __init__(self, cell):\n"
        "        super().__init__(f'{cell} missed its deadline')\n",
        "fake.py") == []


def test_lint_fires_on_silent_seam_function():
    """A SEAM_DEFS function (quarantine/retry/evict site) without any
    emit call is a finding; with one, it is clean."""
    mod, _ = _load_lint()
    findings = mod.scan_source(
        "def retry_transient(fn, policy):\n"
        "    return fn()\n", "fake.py")
    assert [line for _, line, _ in findings] == [1]
    assert mod.scan_source(
        "def retry_transient(fn, policy):\n"
        "    emit_event('RETRY_TRANSIENT', label='x')\n"
        "    return fn()\n", "fake.py") == []


def test_lint_requires_emit_in_index_and_surrogate_seams():
    """The ISSUE 17 seams — index rebuilds and surrogate escalations —
    are journal-bearing: stripping their event emit must be a lint
    failure, structurally."""
    mod, _ = _load_lint()
    assert "_index_rebuilt" in mod.SEAM_DEFS
    assert "_surrogate_escalate" in mod.SEAM_DEFS
    findings = mod.scan_source(
        "def _index_rebuilt(self, group, entries, reason):\n"
        "    self.rebuilds += 1\n", "fixture.py")
    assert len(findings) == 1 and "seam function" in findings[0][2]
    findings = mod.scan_source(
        "def _surrogate_escalate(self, q, reason):\n"
        "    return reason\n", "fixture.py")
    assert len(findings) == 1 and "seam function" in findings[0][2]
    assert mod.scan_source(
        "def _surrogate_escalate(self, q, reason):\n"
        "    self._obs.event('SURROGATE_ESCALATED', reason=reason)\n"
        "    return reason\n", "fixture.py") == []


def test_lint_requires_emit_in_durability_seams():
    """The ISSUE 18 seams — WAL replay, snapshot compaction, quorum
    loss, resync, disk-fault firing, store degrade — are the DR drills'
    detection evidence: stripping any of their emits must be a lint
    failure, structurally."""
    mod, _ = _load_lint()
    for seam in ("_fire_disk_fault", "_recover_state", "_compact",
                 "_quorum_lost", "_read_repair", "_resync_replica",
                 "_degrade_memory_only"):
        assert seam in mod.SEAM_DEFS, seam
    findings = mod.scan_source(
        "def _recover_state(self):\n"
        "    self._seq = 7\n", "fixture.py")
    assert len(findings) == 1 and "seam function" in findings[0][2]
    # the backends' ``_emit`` wrapper counts as emission evidence
    assert mod.scan_source(
        "def _compact(self):\n"
        "    self._emit('SNAPSHOT_COMPACT', seq=self._seq)\n",
        "fixture.py") == []


def test_lint_fires_on_unjournaled_coordination_unavailable():
    """``CoordinationUnavailable`` joined TYPED_ERRORS: constructing it
    without a journal event is a finding (the partition drills' ledger
    would otherwise be unfalsifiable)."""
    mod, _ = _load_lint()
    assert "CoordinationUnavailable" in mod.TYPED_ERRORS
    findings = mod.scan_source(
        "def read(self):\n"
        "    raise CoordinationUnavailable('no quorum')\n", "fake.py")
    assert [line for _, line, _ in findings] == [2]
    assert mod.scan_source(
        "def read(self):\n"
        "    self._emit('QUORUM_LOST', reachable=1)\n"
        "    raise CoordinationUnavailable('no quorum')\n",
        "fake.py") == []
