#!/bin/sh
# Reproduction entry point (the reference's reproduce.sh:1-4 pins deps and
# re-executes the notebook; here deps are baked into the image and the
# driver is a script).  Regenerates the full reference output surface —
# equilibrium stats, Figures/*.{png,jpg,pdf,svg}, runtime.txt, results.json —
# and then runs the test suite.
#
# Test profiles (pytest.ini): the default here is the fast profile
# (-m "not slow", ~1 min on this box); set FULL_SUITE=1 for every test
# including the heavyweight equilibrium solves (~15-20 min single-core).
set -e
cd "$(dirname "$0")"
python reproduce.py "$@"
if [ "${FULL_SUITE:-0}" = "1" ]; then
    python -m pytest tests/ -q
else
    python -m pytest tests/ -q -m "not slow"
fi
