#!/bin/sh
# Reproduction entry point (the reference's reproduce.sh:1-4 pins deps and
# re-executes the notebook; here deps are baked into the image and the
# driver is a script).  Regenerates the full reference output surface —
# equilibrium stats, Figures/*.{png,jpg,pdf,svg}, runtime.txt, results.json —
# and then runs the test suite.
set -e
cd "$(dirname "$0")"
python reproduce.py "$@"
python -m pytest tests/ -q
